"""Serve a mixed-length request stream through the inference subsystem.

Usage:
    python scripts/serve.py [--requests N] [--oversize K]
        [--buckets 12,24] [--batch-size 2] [--max-wait-ms 5]
        [--max-queue-depth 64] [--bf16] [--checkpoint DIR] [--cpu]
        [--metrics SERVE.jsonl] [--out SUMMARY.json] [--seed S]
        [--replicas N] [--swap-at K]

Startup: restore params (params-only — optimizer state never
materializes) or init a toy model, AOT-compile one executable per
bucket, arm the compile-event watchdog. Serve loop: admit -> enqueue ->
micro-batch (flush on full or deadline) -> answer. Close: a
SESSION_SUMMARY-style report.

This doubles as the `make serve-smoke` gate, exiting non-zero when
  * the telemetry stream fails schema validation, or
  * any post-warmup compile event fired (the AOT contract: a
    mixed-length stream over precompiled buckets must compile NOTHING),
  * or an in-range request failed to produce a result.

`--replicas N` (N > 1) switches to the multi-replica continuous-
batching router (se3_transformer_tpu.serving): N replica workers, each
owning its own AOT engine, least-outstanding dispatch, requests
admitted into in-flight bucket slots (deadline only as a fallback),
and — with `--swap-at K` — one rolling weight swap after the K-th
request (fresh seeded params; zero recompiles, zero dropped requests).
This is the `make serve-multi-smoke` gate; on top of the single-replica
gates it also exits non-zero when
  * no request was ever admitted into an in-flight slot
    (continuous_admissions == 0 — the router degenerated to flush
    barriers), or
  * the rolling swap did not complete across every replica.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from se3_transformer_tpu.utils.compilation_cache import (  # noqa: E402
    enable_compilation_cache,
)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description='bucketed AOT serving over a mixed-length stream')
    ap.add_argument('--requests', type=int, default=8,
                    help='in-range requests, lengths cycling across '
                         'buckets (mixed-length by construction)')
    ap.add_argument('--oversize', type=int, default=1,
                    help='extra requests longer than the largest bucket '
                         '(must be rejected, never compiled)')
    ap.add_argument('--buckets', type=str, default='12,24')
    ap.add_argument('--batch-size', type=int, default=2)
    ap.add_argument('--max-wait-ms', type=float, default=5.0)
    ap.add_argument('--max-queue-depth', type=int, default=64)
    ap.add_argument('--flush-every', type=int, default=2,
                    help='emit a serve record every N dispatched batches')
    ap.add_argument('--bf16', action='store_true',
                    help='bf16 activation path (coords cast in, f32 out)')
    ap.add_argument('--precision', type=str, default=None,
                    help='weight-precision mix (quant.rules: fp32 / '
                         'bf16 / int8_mix / fp8_mix). Params quantize '
                         'at restore time — the fp32 tree never lands '
                         'on device. With --replicas N, a comma list '
                         'builds a HETEROGENEOUS fleet (cycled across '
                         'replicas, e.g. "fp32,int8_mix"); rolling '
                         'swaps re-quantize per replica at its own mix '
                         '(zero drops, zero recompiles)')
    ap.add_argument('--checkpoint', type=str, default=None,
                    help='CheckpointManager directory; params-only '
                         'restore (optimizer state is never read)')
    ap.add_argument('--metrics', type=str, default=None,
                    help='JSONL telemetry stream (serve records)')
    ap.add_argument('--out', type=str, default=None,
                    help='write the summary report JSON here')
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--cpu', action='store_true',
                    help='force the CPU backend')
    ap.add_argument('--replicas', type=int, default=1,
                    help='>1 routes through the multi-replica '
                         'continuous-batching router '
                         '(se3_transformer_tpu.serving)')
    ap.add_argument('--swap-at', type=int, default=None,
                    help='multi-replica only: after this many submitted '
                         'requests, hot-swap fresh weights with a '
                         'rolling drain (zero recompiles, zero drops)')
    ap.add_argument('--async-dispatch', action='store_true',
                    help='multi-replica only: per-replica thread-pool '
                         'dispatch — replica executions overlap instead '
                         'of serializing through the submit loop '
                         '(serving.ReplicaWorker async_dispatch)')
    ap.add_argument('--timeout-s', type=float, default=None,
                    help='multi-replica only: per-request deadline '
                         '(submitted_at + timeout); expired requests '
                         'shed before dispatch and resolve with a '
                         'structured RequestFailed("deadline")')
    ap.add_argument('--max-retries', type=int, default=1,
                    help='multi-replica only: redispatches of a failed '
                         "batch's requests onto sibling replicas before "
                         'a structured RequestFailed("retries_'
                         'exhausted")')
    return ap.parse_args(argv)


def precision_mixes(args):
    """The per-replica precision list: None -> fp32 everywhere; a
    single mix applies to every replica; a comma list cycles."""
    if not args.precision:
        return [None] * max(args.replicas, 1)
    mixes = [m.strip() or None for m in args.precision.split(',')]
    if args.replicas <= 1 and len(mixes) > 1:
        raise SystemExit('--precision got a comma list but --replicas '
                         'is 1 — heterogeneous mixes need a fleet')
    return [mixes[i % len(mixes)] for i in range(max(args.replicas, 1))]


def build_module_and_params(args, buckets, seed=None):
    """Toy module + params (checkpoint restore or seeded init) — shared
    by the single-replica and router paths."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.native.loader import chain_adjacency
    from se3_transformer_tpu.training.denoise import DenoiseConfig

    seed = args.seed if seed is None else seed
    cfg = DenoiseConfig(num_tokens=24, dim=8, dim_head=8, heads=2, depth=2,
                        num_degrees=2, max_sparse_neighbors=4)
    module = cfg.build_module()
    rng = np.random.RandomState(seed)
    if args.checkpoint:
        from se3_transformer_tpu.training.checkpoint import CheckpointManager
        params = CheckpointManager(args.checkpoint).restore_params()
        print(f'restored params-only from {args.checkpoint}')
    else:
        L = buckets[0]
        params = module.init(
            jax.random.PRNGKey(seed),
            jnp.asarray(rng.randint(0, cfg.num_tokens, size=(1, L))),
            jnp.asarray(rng.normal(size=(1, L, 3)).astype(np.float32)),
            mask=jnp.ones((1, L), bool),
            adj_mat=jnp.asarray(chain_adjacency(L)),
            return_type=1)['params']
        print(f'no --checkpoint: initialized fresh params (seed {seed})')
    return cfg, module, params


def request_lengths(args, buckets, max_len, rng):
    """Mixed-length stream: in-range lengths cycling across buckets,
    plus the oversize (must-reject) tail, shuffled."""
    lows = [1] + [b + 1 for b in buckets[:-1]]
    lengths = [int(rng.randint(lows[i % len(buckets)],
                               buckets[i % len(buckets)] + 1))
               for i in range(args.requests)]
    lengths += [max_len + int(rng.randint(1, 32))
                for _ in range(args.oversize)]
    rng.shuffle(lengths)
    return lengths


def main(argv=None):
    args = parse_args(argv)
    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')
    enable_compilation_cache()
    if args.replicas > 1:
        return serve_multi(args)
    import numpy as np

    from se3_transformer_tpu.inference import (
        AdmissionController, InferenceEngine, MicroBatcher,
        RequestRejected, ServeTelemetry,
    )
    from se3_transformer_tpu.observability import MetricLogger
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    import jax.numpy as jnp

    buckets = tuple(int(b) for b in args.buckets.split(','))
    cfg, module, params = build_module_and_params(args, buckets)

    # ---- startup: AOT-compile every bucket, then arm the watchdog ---- #
    t0 = time.perf_counter()
    engine = InferenceEngine(
        module, params, buckets=buckets, batch_size=args.batch_size,
        return_type=1, precision=precision_mixes(args)[0],
        activation_dtype=jnp.bfloat16 if args.bf16 else None)
    print(f'warmup: compiled {len(engine.executables)} bucket '
          f'executables in {time.perf_counter() - t0:.1f}s '
          f'({engine.compile_seconds}, precision '
          f'{engine.precision_name})')

    admission = AdmissionController(max_len=engine.max_len,
                                    max_queue_depth=args.max_queue_depth)
    batcher = MicroBatcher(engine.run, buckets=engine.buckets,
                           batch_size=args.batch_size,
                           max_wait_ms=args.max_wait_ms,
                           admission=admission)
    logger = MetricLogger(args.metrics, run_meta=dict(
        mode='serve', buckets=list(buckets), batch_size=args.batch_size,
        dtype=engine.dtype_name, precision=engine.precision_name))
    telemetry = ServeTelemetry(engine, batcher, admission, logger)
    telemetry.arm()

    # ---- the request stream: lengths cycle across buckets ----------- #
    rng = np.random.RandomState(args.seed)
    lengths = request_lengths(args, engine.buckets, engine.max_len, rng)

    pending, flushed_at = [], 0
    for length in lengths:
        tokens = rng.randint(0, cfg.num_tokens, size=length)
        coords = rng.normal(size=(length, 3)).astype(np.float32)
        try:
            pending.append(batcher.submit(tokens, coords))
        except RequestRejected as e:
            print(f'rejected: {e.code} {e.detail}')
            logger.log_record('step', mirror=False, step=len(pending),
                              rejected=e.to_record())
        batcher.pump()
        if batcher.batches_dispatched - flushed_at >= args.flush_every:
            telemetry.flush()
            flushed_at = batcher.batches_dispatched
    # deadline-drain the stragglers, then close the stream
    while batcher.queue_depth:
        wait = batcher.next_deadline()
        if wait:
            time.sleep(wait)
        batcher.pump()
    telemetry.flush()
    summary = telemetry.close()
    logger.close()

    # ---- gates + report --------------------------------------------- #
    ok = True
    unanswered = [p.request_id for p in pending if not p.ok]
    if unanswered:
        print(f'FAIL: {len(unanswered)} admitted requests unanswered')
        ok = False
    if telemetry.post_warmup_compiles:
        print(f'FAIL: {telemetry.post_warmup_compiles} compile events '
              f'after warmup — the AOT bucket contract is broken')
        ok = False
    if args.metrics:
        try:
            info = validate_stream(args.metrics)
            print(f'schema ok: {info["records"]} records {info["kinds"]}')
        except SchemaError as e:
            print(f'FAIL: telemetry stream invalid: {e}')
            ok = False

    report = dict(
        ok=ok,
        requests=dict(total=len(lengths), answered=len(pending) -
                      len(unanswered), **admission.snapshot()),
        batches=batcher.batches_dispatched,
        post_warmup_compiles=telemetry.post_warmup_compiles,
        compile_seconds=engine.stats()['compile_seconds'],
        # memory-per-bucket off the warmup cost ledger (the full
        # schema'd cost records are in the --metrics stream)
        peak_hbm_by_bucket=engine.stats()['peak_hbm_by_bucket'],
        latency_by_bucket={
            k: {p: v[p] for p in
                ('count', 'p50_ms', 'p95_ms', 'p99_ms', 'max_ms')}
            for k, v in summary['timing'].items()
            if k.startswith('bucket_')},
        request_latency_ms=summary['metrics']['request_latency_ms'],
        batch_fill=summary['metrics'].get('batch_fill'),
    )
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report, f, indent=2)
        print(f'report -> {args.out}')
    return 0 if ok else 1


def serve_multi(args):
    """Multi-replica continuous-batching path (`--replicas N`)."""
    import numpy as np

    from se3_transformer_tpu.inference import (
        AdmissionController, InferenceEngine, RequestRejected,
    )
    from se3_transformer_tpu.observability import MetricLogger, PhaseTimer
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    from se3_transformer_tpu.serving import (
        ReplicaWorker, Router, RouterTelemetry,
    )
    import jax.numpy as jnp

    buckets = tuple(int(b) for b in args.buckets.split(','))
    cfg, module, params = build_module_and_params(args, buckets)

    # ---- startup: N replicas, ONE shared PhaseTimer (the aggregate
    # per-bucket SLO surface), every bucket AOT-compiled per replica --- #
    t0 = time.perf_counter()
    timer = PhaseTimer()
    mixes = precision_mixes(args)
    engines = [InferenceEngine(
        module, params, buckets=buckets, batch_size=args.batch_size,
        return_type=1, timer=timer, precision=mixes[i],
        activation_dtype=jnp.bfloat16 if args.bf16 else None)
        for i in range(args.replicas)]
    print(f'warmup: {args.replicas} replicas x '
          f'{len(engines[0].executables)} bucket executables in '
          f'{time.perf_counter() - t0:.1f}s (precision mixes '
          f'{[e.precision_name for e in engines]})')

    workers = [ReplicaWorker(i, e, max_wait_ms=args.max_wait_ms,
                             async_dispatch=args.async_dispatch)
               for i, e in enumerate(engines)]
    admission = AdmissionController(max_len=buckets[-1],
                                    max_queue_depth=args.max_queue_depth)
    # the router is a context manager: its dispatch executors shut down
    # when the block exits, ON ERROR PATHS TOO — a crashed serve loop
    # must not leak replica threads
    with Router(workers, admission=admission,
                max_retries=args.max_retries,
                default_timeout_s=args.timeout_s) as router:
        # materialize the swap weights BEFORE arming the compile
        # watchdog: a real rolling reload restores numpy leaves off the
        # async-checkpoint path (zero compiles); the smoke's stand-in —
        # a fresh seeded init — compiles eager init programs, which
        # must land in the warmup window, not against the AOT contract
        swap_params = None
        if args.swap_at is not None:
            _, _, swap_params = build_module_and_params(
                args, buckets, seed=args.seed + 1)
        logger = MetricLogger(args.metrics, run_meta=dict(
            mode='serve_multi', replicas=args.replicas,
            buckets=list(buckets), batch_size=args.batch_size,
            dtype=engines[0].dtype_name,
            precision_mixes=[e.precision_name for e in engines]))
        telemetry = RouterTelemetry(router, admission, logger)
        telemetry.arm()

        # ---- the request stream, with one mid-run rolling swap ------ #
        rng = np.random.RandomState(args.seed)
        lengths = request_lengths(args, buckets, router.max_len, rng)

        pending, flushed_at, swapped = [], 0, False
        for i, length in enumerate(lengths):
            if args.swap_at is not None and i == args.swap_at \
                    and not swapped:
                # same shapes, new values: the swap must compile
                # NOTHING and drop NOTHING (the gates below prove both)
                events = router.swap_weights(swap_params,
                                             tag=f'seed_{args.seed + 1}')
                swapped = True
                print(f'rolling weight swap after request {i}: '
                      f'{len(events)} replicas swapped, '
                      f'{sum(e["drained_batches"] for e in events)} '
                      f'partial batches drained')
            tokens = rng.randint(0, cfg.num_tokens, size=length)
            coords = rng.normal(size=(length, 3)).astype(np.float32)
            try:
                pending.append(router.submit(tokens, coords))
            except RequestRejected as e:
                print(f'rejected: {e.code} {e.detail}')
                logger.log_record('step', mirror=False,
                                  step=len(pending),
                                  rejected=e.to_record())
            router.pump()
            if router.batches_dispatched - flushed_at >= args.flush_every:
                telemetry.flush()
                flushed_at = router.batches_dispatched
        # deadline-drain the stragglers, then close the stream
        while router.queue_depth:
            wait = router.next_deadline()
            if wait:
                time.sleep(wait)
            elif args.async_dispatch:
                # async mode: queue_depth includes executor-inflight
                # rows that no deadline governs — yield, don't spin
                time.sleep(0.001)
            router.pump()
    # __exit__ barriered on any async dispatches and shut the
    # executors down (no-op for synchronous replicas)
    telemetry.flush()
    summary = telemetry.close()
    logger.close()

    # ---- gates + report --------------------------------------------- #
    ok = True
    unanswered = [p.request_id for p in pending if not p.ok]
    if unanswered:
        print(f'FAIL: {len(unanswered)} admitted requests unanswered '
              f'(the rolling swap must drop NOTHING)')
        ok = False
    if telemetry.post_warmup_compiles:
        print(f'FAIL: {telemetry.post_warmup_compiles} compile events '
              f'after warmup — a weight swap or mixed-length stream '
              f'broke the AOT contract')
        ok = False
    if not router.continuous_admissions:
        print('FAIL: zero continuous admissions — no request ever '
              'joined an in-flight bucket slot, the router degenerated '
              'to flush barriers')
        ok = False
    if args.swap_at is not None and \
            len(router.swap_events) != args.replicas:
        print(f'FAIL: rolling swap incomplete: '
              f'{len(router.swap_events)} swap events for '
              f'{args.replicas} replicas')
        ok = False
    if args.metrics:
        try:
            info = validate_stream(args.metrics)
            print(f'schema ok: {info["records"]} records {info["kinds"]}')
        except SchemaError as e:
            print(f'FAIL: telemetry stream invalid: {e}')
            ok = False

    report = dict(
        ok=ok,
        replicas=args.replicas,
        precision_mixes=[e.precision_name for e in engines],
        requests=dict(total=len(lengths), answered=len(pending) -
                      len(unanswered), **admission.snapshot()),
        batches=router.batches_dispatched,
        continuous_admissions=router.continuous_admissions,
        deadline_flushes=router.deadline_flushes,
        swaps=dict(count=len(router.swap_events),
                   events=router.swap_events),
        post_warmup_compiles=telemetry.post_warmup_compiles,
        per_replica={str(w.id): w.snapshot() for w in router.workers},
        latency_by_bucket={
            k: {p: v[p] for p in
                ('count', 'p50_ms', 'p95_ms', 'p99_ms', 'max_ms')}
            for k, v in summary['timing'].items()
            if k.startswith('bucket_')},
        request_latency_ms=summary['metrics']['request_latency_ms'],
    )
    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report, f, indent=2)
        print(f'report -> {args.out}')
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
