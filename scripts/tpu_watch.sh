#!/bin/bash
# Retry the TPU every 10 min; on recovery run the on-chip validation and
# benchmark once, then exit. Safe to leave running: the probe holds the
# chip only briefly, and the script exits after one successful pass.
cd "$(dirname "$0")/.." || exit 1
LOG=${1:-/tmp/tpu_watch.log}
for i in $(seq 1 18); do
  echo "[tpu_watch] attempt $i $(date -u +%H:%M:%S)" >> "$LOG"
  # the probe must see an actual TPU device — JAX can silently fall back
  # to CPU when the platform is unset, which would fake a recovery
  if timeout 120 python -u -c "import jax; print(jax.devices())" 2>>"$LOG" \
      | tee -a "$LOG" | grep -qi "tpu"; then
    echo "[tpu_watch] TPU RECOVERED — running checks + bench" >> "$LOG"
    timeout 1200 python scripts/tpu_checks.py >> "$LOG" 2>&1
    timeout 1800 python bench.py >> "$LOG" 2>&1
    echo "[tpu_watch] done" >> "$LOG"
    exit 0
  fi
  sleep 600
done
echo "[tpu_watch] gave up" >> "$LOG"
