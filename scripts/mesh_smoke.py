"""CPU gate for composed dp x sp x tp parallelism (`make mesh-smoke`).

The ROADMAP item 4 acceptance harness: the one-mesh composed train step
must RUN (the jax-0.4.37 GSPMD donation bug killed the unpinned route),
match plain data parallelism bit-for-bit-ish, stay all-gather-free on
the sequence axis with tp live, and bank a schema'd `mesh_sweep` record
the committed per-axis budgets judge.

Four gates, exit non-zero on any failure:

  1. PARITY — one composed (2,2,2) update vs the IDENTICAL global
     problem as dp-only (2,1,1), same params, same pre-drawn noise
     (in-step `jax.random` is sharding-dependent on this jax, so the
     noise rides in the batch). Loss and every updated param leaf
     <= 1e-5. This is the fast tier-1 sibling's check, re-proven at
     smoke time.
  2. ALL-GATHER-FREE — the flagship_fast composed (2,2,2) ring point
     (scripts/width_table.py mesh_sweep_point) compiles with ZERO
     sp-varying full-width all-gathers in its partitioned HLO
     (parallel.exchange.analyze_hlo_comm with the axis-aware scan:
     dp weight prefetches and tp channel gathers are placement
     traffic; only sp-group gathers can rematerialize the sequence).
  3. SCHEMA — the measured row validates as kind='mesh_sweep'
     (observability.schema): per-axis collective split present, comm
     mesh echoing the row's (dp, sp, tp), finite loss, executed
     wall-clock.
  4. BUDGETS — scripts/perf_gate.py judges the banked stream against
     PERF_BUDGETS.json (per-axis byte ceilings at (2,2,2), the
     every-point all-gather-free proof bit, the per-shard memory
     ceiling).

`--inject-regression` instead writes a schema-VALID but corrupted row
(all_gather_free False with sp-group gather shapes, inflated per-axis
bytes, per-shard memory over the ceiling) and requires `perf_gate.py`
to FIRE on it, then exits 1 — proving the committed budgets actually
bite (the Makefile asserts rc==1).

    python scripts/mesh_smoke.py [--metrics MESH.jsonl] [--pdn 32]
        [--inject-regression]
"""
import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

PARITY_TOL = 1e-5


def parity_gate(jax):
    """One composed (2,2,2) update vs dp-only (2,1,1) on the identical
    global problem: same init, same batch, same pre-drawn noise.
    Returns the gate evidence dict; asserts loudly on breach."""
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from se3_transformer_tpu import SE3TransformerModule
    from se3_transformer_tpu.parallel import make_mesh
    from se3_transformer_tpu.parallel.sharding import (
        composed_state_shardings, make_sharded_train_step,
    )

    module = SE3TransformerModule(dim=8, depth=1, attend_self=True,
                                  num_neighbors=4, num_degrees=2,
                                  output_degrees=2, heads=2, dim_head=4)
    rng = np.random.RandomState(0)
    b, n = 2, 16
    feats = jnp.asarray(rng.normal(size=(b, n, 8)), jnp.float32)
    coors = jnp.asarray(rng.normal(size=(b, n, 3)), jnp.float32)
    mask = jnp.ones((b, n), bool)
    params0 = jax.jit(module.init, static_argnames=('return_type',))(
        jax.random.PRNGKey(0), feats, coors, mask=mask,
        return_type=1)['params']
    opt = optax.adam(1e-3)
    noise0 = jax.random.normal(jax.random.PRNGKey(1), coors.shape)

    def loss_fn(params, batch, key):
        del key  # noise is data: in-jit rng is sharding-dependent here
        noise = batch['noise']
        out = module.apply({'params': params}, batch['feats'],
                           batch['coors'] + noise, mask=batch['mask'],
                           return_type=1)
        return ((out - noise[:, :, None, :]) ** 2).mean(), {}

    def run(mesh, composed):
        # fresh buffers per arm: device_put onto a replicated spec can
        # alias the source buffer, and the steps donate their state
        params = jax.tree_util.tree_map(jnp.array, params0)
        if composed:
            params, opt_state, shardings = composed_state_shardings(
                params, opt.init(params), mesh)
            step = make_sharded_train_step(loss_fn, opt, mesh=mesh,
                                           state_shardings=shardings)
        else:
            opt_state = jax.jit(opt.init)(params)
            step = make_sharded_train_step(loss_fn, opt, mesh=mesh)
        node = P('dp', 'sp', None) if composed else P('dp', None, None)
        flat = P('dp', 'sp') if composed else P('dp', None)
        batch = {
            'feats': jax.device_put(feats, NamedSharding(mesh, node)),
            'coors': jax.device_put(coors, NamedSharding(mesh, node)),
            'noise': jax.device_put(noise0, NamedSharding(mesh, node)),
            'mask': jax.device_put(mask, NamedSharding(mesh, flat)),
        }
        params, _, loss, _ = step(params, opt_state, batch,
                                  jax.random.PRNGKey(2))
        return float(loss), params

    loss_c, params_c = run(make_mesh(dp=2, sp=2, tp=2), composed=True)
    loss_d, params_d = run(make_mesh(jax.devices()[:2], dp=2, sp=1, tp=1),
                           composed=False)
    # pull to host first: the arms live on different meshes (8 vs 2
    # devices) and jnp ops refuse cross-mesh operands
    max_abs = max(
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree_util.tree_leaves(params_c),
                        jax.tree_util.tree_leaves(params_d)))
    assert abs(loss_c - loss_d) <= PARITY_TOL * max(1.0, abs(loss_d)), \
        f'PARITY breach: composed loss {loss_c} vs dp-only {loss_d}'
    assert max_abs <= PARITY_TOL, \
        f'PARITY breach: updated params diverge by {max_abs}'
    n_tp = sum(
        1 for leaf in jax.tree_util.tree_leaves(params_c)
        if 'tp' in str(getattr(leaf.sharding, 'spec', '')))
    assert n_tp >= 4, f'only {n_tp} params tp-sharded (cosmetic mesh?)'
    return dict(parity_loss_composed=round(loss_c, 6),
                parity_loss_dp_only=round(loss_d, 6),
                parity_max_abs=float(f'{max_abs:.3g}'),
                parity_tp_sharded_params=n_tp)


def _corrupted_row(pdn):
    """Schema-valid mesh_sweep row with every budgeted claim broken:
    sp-group full-width gathers back, per-axis bytes inflated past the
    committed ceilings, per-shard memory over the cap."""
    big = dict(count=99, bytes=50_000_000)
    return dict(
        kind='mesh_sweep', dp=2, sp=2, tp=2, devices=8,
        n=pdn * 2, per_device_nodes=pdn, step_s=1.0,
        per_shard_total_gb=0.9, loss_finite=True,
        injected=True,
        comm=dict(
            sp=2, ring_steps=2, overlap=True, exchange=True,
            collectives={'all-gather': big, 'all-reduce': big,
                         'collective-permute': big},
            full_width_all_gathers=[f'f32[1,{pdn * 2},8,3]'] * 4,
            all_gather_free=False,
            axis_collectives={
                'sp': {'collective-permute': big, 'all-reduce': big},
                'dp': {'all-reduce': big},
                'tp': {'all-reduce': big},
            },
            mesh=dict(dp=2, sp=2, tp=2),
        ),
    )


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--metrics',
                    default=os.path.join('/tmp', 'mesh_smoke.jsonl'))
    ap.add_argument('--pdn', type=int, default=32,
                    help='per-device nodes of the measured (2,2,2) row')
    ap.add_argument('--inject-regression', action='store_true')
    args = ap.parse_args(argv)

    import width_table
    jax = width_table._setup(8)

    import perf_gate
    from se3_transformer_tpu.observability.report import write_record_stream
    from se3_transformer_tpu.observability.schema import validate_record

    if args.inject_regression:
        row = _corrupted_row(args.pdn)
        validate_record(dict(row, run_id='inject'))
        write_record_stream(args.metrics, f'mesh_inject_{os.getpid()}',
                            [row])
        rc = perf_gate.main([args.metrics])
        if rc != 1:
            print(f'mesh-smoke INJECTION NOT CAUGHT: perf_gate rc={rc} '
                  f'on a corrupted row — the committed budgets are not '
                  f'biting', file=sys.stderr)
            sys.exit(2)
        print('mesh-smoke injection: perf_gate FIRED as required')
        sys.exit(1)

    evidence = parity_gate(jax)
    print(f'mesh-smoke parity ok: {json.dumps(evidence)}')

    row = width_table.mesh_sweep_point(jax, 2, 2, 2, args.pdn,
                                       dim=16, k=8, steps=2)
    comm = row['comm']
    assert comm['all_gather_free'], \
        f'ALL-GATHER-FREE breach: {comm["full_width_all_gathers"]}'
    assert row['loss_finite'], 'non-finite loss on the composed point'
    assert comm.get('axis_collectives'), 'per-axis split missing'
    row = dict(row, kind='mesh_sweep', **evidence)
    validate_record(dict(row, run_id='pre'))   # fail BEFORE banking
    write_record_stream(args.metrics, f'mesh_smoke_{os.getpid()}', [row])
    print(f'mesh-smoke banked {args.metrics}: (2,2,2) pdn={args.pdn} '
          f'step_s={row["step_s"]} per_shard_gb='
          f'{row["per_shard_total_gb"]} all_gather_free=True')

    rc = perf_gate.main([args.metrics])
    if rc != 0:
        print('mesh-smoke: committed budgets FAILED on the fresh row',
              file=sys.stderr)
        sys.exit(rc)
    print('mesh-smoke ok: parity + all-gather-free + schema + budgets')


if __name__ == '__main__':
    main()
