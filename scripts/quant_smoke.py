"""CPU gate for the quantized serving precision layer (`make quant-smoke`).

Four gates, exit non-zero on any failure:

  1. IMPLEMENTATION PARITY — the quantized-mix engine must agree with
     the fp32 REFERENCE EVALUATION of the same quantized weights
     within 1e-4 max-abs on padded AND unpadded inputs: the fused
     dequant epilogues / kernels / engine plumbing must add NOTHING
     beyond quantization itself. (The error vs the raw fp32 model is
     the accuracy tradeoff a mix buys its memory with — banked in the
     record as `quant_error_max_abs`, never gated at 1e-4: any int8
     weight grid carries ~0.4% relative rounding by construction.)
  2. EQUIVARIANCE — equivariance-L2 of the quantized model at the
     swept degrees (default 2,4) must stay under 1e-4: weight-only
     quantization must preserve equivariance to roundoff (the int8
     rules are restricted to invariant-input matmuls; an l>0 weight
     matched by an int8 rule raises before anything runs).
  3. MEMORY — argument bytes of the quantized engine's largest-bucket
     executable must be <= 0.6x the fp32 engine's, read off the PR 6
     cost ledger (the per-replica memory claim that multiplies
     ROADMAP items 4-5's replica counts).
  4. SCHEMA + RECORD — the A/B payload from bench.quant_main is
     written as a schema'd `quant_ab` record; the Makefile target then
     runs `obs_report --require quant_ab` and `perf_gate.py` on the
     stream so the committed budgets judge the fresh numbers.

    python scripts/quant_smoke.py [--metrics QUANT.jsonl]
        [--mix int8_mix] [--steps 5]
"""
import argparse
import json
import os
import sys
import uuid

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

PARITY_TOL = 1e-4
EQ_TOL = 1e-4
ARG_BYTES_CEILING = 0.6


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='quantized-serving parity + equivariance + memory '
                    'record gate')
    ap.add_argument('--metrics', default=None,
                    help='write the schema-valid quant_ab stream here')
    ap.add_argument('--mix', default='int8_mix',
                    help='precision mix (quant.rules.MIXES)')
    ap.add_argument('--steps', type=int, default=5)
    args = ap.parse_args(argv)

    import jax
    jax.config.update('jax_platforms', 'cpu')

    import bench

    record = bench.quant_main(mix=args.mix, steps=args.steps)

    ok = True
    if record['parity_max_abs'] >= PARITY_TOL:
        print(f'FAIL: implementation parity {record["parity_max_abs"]} '
              f'>= {PARITY_TOL} — the quantized serving path added '
              f'error beyond quantization itself')
        ok = False
    if record['equivariance_l2'] >= EQ_TOL:
        print(f'FAIL: quantized equivariance L2 '
              f'{record["equivariance_l2"]} >= {EQ_TOL} at degrees '
              f'{sorted(record["equivariance_by_degree"])}')
        ok = False
    if record['argument_bytes_ratio'] > ARG_BYTES_CEILING:
        print(f'FAIL: argument-bytes ratio '
              f'{record["argument_bytes_ratio"]} > {ARG_BYTES_CEILING} '
              f'— the mix did not buy its memory claim '
              f'(fp32 {record["argument_bytes_fp32"]} B vs quant '
              f'{record["argument_bytes_quant"]} B)')
        ok = False

    if args.metrics:
        from se3_transformer_tpu.observability.report import (
            write_record_stream,
        )
        from se3_transformer_tpu.observability.schema import (
            validate_stream,
        )
        body = dict(kind='quant_ab', label=record['metric'],
                    mix=record['mix'], buckets=record['buckets'],
                    argument_bytes_fp32=record['argument_bytes_fp32'],
                    argument_bytes_quant=record['argument_bytes_quant'],
                    argument_bytes_ratio=record['argument_bytes_ratio'],
                    params_bytes_ratio=record['params_bytes_ratio'],
                    quant_report=record['quant_report'],
                    parity_max_abs=record['parity_max_abs'],
                    quant_error_max_abs=record['quant_error_max_abs'],
                    equivariance_l2=record['equivariance_l2'],
                    equivariance_by_degree=record[
                        'equivariance_by_degree'],
                    value=record['value'], unit=record['unit'],
                    timing=record['timing'], cost=record['cost'])
        write_record_stream(args.metrics,
                            f'quant_smoke_{uuid.uuid4().hex[:8]}',
                            [body])
        info = validate_stream(args.metrics)
        print(f'schema ok: {info["records"]} records {info["kinds"]}')

    summary = dict(ok=ok, mix=record['mix'],
                   argument_bytes_ratio=record['argument_bytes_ratio'],
                   parity_max_abs=record['parity_max_abs'],
                   quant_error_max_abs=record['quant_error_max_abs'],
                   equivariance_l2=record['equivariance_l2'],
                   buckets=record['buckets'])
    print(json.dumps(summary))
    if not ok:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
