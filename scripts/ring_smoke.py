"""Sequence-parallel comm smoke (`make ring-smoke`).

Virtual-8-device CPU mesh, one small ring-path model (padded mask +
bonded adjacency — the semantics that must survive the sparse exchange),
three gates, exit non-zero on any miss:

  1. EXCHANGE PARITY — the neighbor-sparse exchange arm
     (ring_exchange=True, the default) matches the dense-gather control
     arm (ring_exchange=False) on the same params/inputs, and the
     overlapped ring matches the serialized ring BIT-EXACTLY
     (parallel.ring.ring_scan's contract).
  2. COMM SCHEMA — the run writes a telemetry stream (run_meta + one
     `comm` record per traced arm) that observability.schema validates;
     the Makefile target re-gates it through
     `scripts/obs_report.py --require-comm`.
  3. ALL-GATHER-FREE — the traced sp=8 forward of the exchange arm
     contains no full-width [b, N, ...] all-gather (the artifact the
     exchange exists to kill), while the dense control arm is REQUIRED
     to contain one (proving the scan actually detects them — a
     detector that never fires gates nothing).

Usage:
    python scripts/ring_smoke.py [--metrics STREAM.jsonl]
"""
import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--metrics', default=None,
                    help='write the schema-valid comm stream here')
    ap.add_argument('--devices', type=int, default=8)
    args = ap.parse_args(argv)

    flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in flags:
        os.environ['XLA_FLAGS'] = (
            flags +
            f' --xla_force_host_platform_device_count={args.devices}'
        ).strip()
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from se3_transformer_tpu import SE3TransformerModule
    from se3_transformer_tpu.parallel import make_mesh
    from se3_transformer_tpu.parallel.exchange import comm_payload
    from se3_transformer_tpu.parallel.ring import ring_knn

    failures = []
    sp = args.devices
    mesh = make_mesh(dp=1, sp=sp, tp=1)
    rng = np.random.RandomState(0)
    n, k = 64, 6
    feats = jnp.asarray(rng.normal(size=(1, n, 8)), jnp.float32)
    coors = jnp.asarray(rng.normal(size=(1, n, 3)) * 2, jnp.float32)
    mask = np.ones((1, n), bool)
    mask[:, n - 8:] = False                      # padded tail
    mask = jnp.asarray(mask)
    adj = np.zeros((n, n), bool)                 # a chain of bonds
    idx_ = np.arange(n - 9)
    adj[idx_, idx_ + 1] = adj[idx_ + 1, idx_] = True
    adj = jnp.asarray(adj[None])

    # gate 1a: overlapped vs serialized ring_knn — bit-exact
    d1, i1 = ring_knn(coors, k, mesh, mask=mask, overlap=True)
    d0, i0 = ring_knn(coors, k, mesh, mask=mask, overlap=False)
    if not (np.array_equal(np.asarray(d1), np.asarray(d0))
            and np.array_equal(np.asarray(i1), np.asarray(i0))):
        failures.append('ring_knn overlap=True vs overlap=False not '
                        'bit-exact')

    # gate 1b: exchange arm vs dense-gather control arm on one model
    kw = dict(dim=8, depth=1, attend_self=True, num_neighbors=k,
              num_degrees=2, output_degrees=2,
              attend_sparse_neighbors=True, max_sparse_neighbors=2,
              sequence_parallel='ring', mesh=mesh)
    arms = {
        'overlapped_sparse': SE3TransformerModule(**kw),
        'serialized_dense': SE3TransformerModule(
            **kw, ring_overlap=False, ring_exchange=False),
    }
    call = dict(mask=mask, adj_mat=adj, return_type=1)
    params = arms['overlapped_sparse'].init(
        jax.random.PRNGKey(7), feats, coors, **call)['params']
    outs = {}
    hlos = {}
    shard = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    sharded = (shard(feats, P(None, 'sp', None)),
               shard(coors, P(None, 'sp', None)),
               shard(mask, P(None, 'sp')), shard(adj, P(None, 'sp', None)))
    for name, module in arms.items():
        compiled = jax.jit(
            lambda p, f, c, m, a, module=module: module.apply(
                {'params': p}, f, c, mask=m, adj_mat=a, return_type=1)
        ).lower(params, *sharded).compile()
        outs[name] = np.asarray(compiled(params, *sharded))
        hlos[name] = compiled.as_text()
    diff = float(np.abs(outs['overlapped_sparse']
                        - outs['serialized_dense']).max())
    if diff > 1e-5:
        failures.append(f'exchange arm vs dense control arm diverge: '
                        f'max diff {diff}')

    # gate 3: the exchange trace is all-gather-free; the dense control
    # trace must NOT be (detector liveness)
    payloads = {}
    for name, (ov, ex) in (('overlapped_sparse', (True, True)),
                           ('serialized_dense', (False, False))):
        payloads[name] = comm_payload(hlos[name], sp=sp, ring_steps=sp,
                                      overlap=ov, exchange=ex,
                                      full_width_dim=n)
    if not payloads['overlapped_sparse']['all_gather_free']:
        failures.append(
            'exchange arm traced full-width all-gathers: '
            f"{payloads['overlapped_sparse']['full_width_all_gathers']}")
    if payloads['serialized_dense']['all_gather_free']:
        failures.append('dense control arm traced NO full-width '
                        'all-gather — the detector cannot be trusted')

    # gate 2: schema'd comm stream
    if args.metrics:
        from se3_transformer_tpu.observability.report import (
            write_comm_stream,
        )
        write_comm_stream(
            args.metrics, f'ring_smoke_{os.getpid()}',
            [dict(payload, label=name)
             for name, payload in payloads.items()])

    summary = dict(
        sp=sp, n=n, k=k, parity_max_diff=diff,
        overlap_bit_exact='ring_knn overlap' not in ' '.join(failures),
        exchange_all_gather_free=payloads[
            'overlapped_sparse']['all_gather_free'],
        dense_full_width_all_gathers=len(payloads[
            'serialized_dense']['full_width_all_gathers']),
        failures=failures,
    )
    print(json.dumps(summary))
    if failures:
        for f_ in failures:
            print(f'RING SMOKE FAIL: {f_}', file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
