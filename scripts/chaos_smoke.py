"""Chaos smoke: the single-host fault domain under deterministic fire.

Usage:
    python scripts/chaos_smoke.py [--replicas 3] [--requests 80]
        [--buckets 8,16] [--batch-size 2] [--max-wait-ms 10]
        [--timeout-s 30] [--max-retries 2] [--seed 0]
        [--swap-at 30] [--ckpt-dir DIR] [--metrics CHAOS.jsonl]
        [--out SUMMARY.json] [--weaken none|drop]

N CPU replicas serve a mixed-length stream while a seeded
`faults.FaultInjector` (same seed, same faults) injects:

  * replica crashes   — replica 0's dispatches 2-4 raise, driving its
    health breaker healthy -> degraded -> QUARANTINED; the router drops
    it from rotation, redispatches the failed batches onto siblings,
    and recovers it via exponential-backoff half-open probe traffic;
  * latency spikes    — every 9th engine run sleeps (the slow-replica
    case: served, slower, no contract change);
  * a torn checkpoint — the checkpoint directory's LATEST step is
    corrupted after its write (`checkpoint_written` corrupt plan); the
    mid-run rolling weight swap hot-reloads from that directory, so
    `restore_params` must fall back to the newest VALID step;
  * instant deadlines — two requests submit with timeout_s=0 and must
    shed before dispatch with a structured RequestFailed('deadline').

Exit is non-zero unless ALL of:
  * zero lost requests: every submit resolves answered or structured-
    error (RequestRejected at the door / RequestFailed after), never
    silence;
  * >= 1 quarantine -> recovery transition was OBSERVED (the breaker
    actually cycled);
  * the rolling swap completed on every replica FROM THE FALLBACK step
    (the corrupt latest was skipped — the swap tag names the step);
  * zero post-warmup compiles (faults must not break the AOT contract);
  * the telemetry stream (serve + the new `fault` records) is
    schema-valid.

`--weaken drop` is the injection arm of the `make chaos-smoke` pair: it
replaces the router's structured-failure choke point with a silent drop
(and zeroes the retry budget), so failed requests are LOST — the run
must then exit rc==1, proving the zero-lost gate fires rather than
decorates. The clean arm must pass AND the weakened arm must fail; any
other combination fails the make target.
"""
import argparse
import atexit
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from se3_transformer_tpu.utils.compilation_cache import (  # noqa: E402
    enable_compilation_cache,
)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description='seeded fault injection over the multi-replica '
                    'serving fault domain (CPU)')
    ap.add_argument('--replicas', type=int, default=3)
    ap.add_argument('--requests', type=int, default=80)
    ap.add_argument('--oversize', type=int, default=1)
    ap.add_argument('--buckets', type=str, default='8,16')
    ap.add_argument('--batch-size', type=int, default=2)
    ap.add_argument('--max-wait-ms', type=float, default=10.0)
    ap.add_argument('--max-queue-depth', type=int, default=256)
    ap.add_argument('--timeout-s', type=float, default=30.0)
    ap.add_argument('--max-retries', type=int, default=2)
    ap.add_argument('--flush-every', type=int, default=8)
    ap.add_argument('--swap-at', type=int, default=None,
                    help='rolling swap_from_checkpoint after this many '
                         'requests (default: requests // 2)')
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--ckpt-dir', type=str, default=None,
                    help='checkpoint dir for the torn-latest swap '
                         '(default: a fresh temp dir, removed after)')
    ap.add_argument('--metrics', type=str, default=None)
    ap.add_argument('--out', type=str, default=None)
    ap.add_argument('--checkpoint', default=None, help=argparse.SUPPRESS)
    ap.add_argument('--weaken', choices=('none', 'drop'), default='none',
                    help="'drop': silently drop after-budget failures "
                         'instead of resolving them structurally — the '
                         'zero-lost gate MUST fire (rc 1), proving it '
                         'is live')
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    import jax
    jax.config.update('jax_platforms', 'cpu')
    enable_compilation_cache()
    import numpy as np

    from serve import build_module_and_params, request_lengths
    from se3_transformer_tpu.faults import FaultInjector
    from se3_transformer_tpu.inference import (
        AdmissionController, InferenceEngine, RequestRejected,
    )
    from se3_transformer_tpu.inference.admission import RequestFailed
    from se3_transformer_tpu.observability import MetricLogger, PhaseTimer
    from se3_transformer_tpu.observability.schema import (
        SchemaError, validate_stream,
    )
    from se3_transformer_tpu.serving import (
        HealthConfig, ReplicaWorker, Router, RouterTelemetry,
    )
    from se3_transformer_tpu.training.checkpoint import CheckpointManager

    buckets = tuple(int(b) for b in args.buckets.split(','))
    swap_at = (args.swap_at if args.swap_at is not None
               else args.requests // 2)
    cfg, module, params = build_module_and_params(args, buckets)
    _, _, swap_params = build_module_and_params(args, buckets,
                                                seed=args.seed + 1)

    # ---- the fault plan (seeded — same seed, same chaos) ------------- #
    inj = FaultInjector(seed=args.seed)
    inj.plan('replica_dispatch', 'exception', match=dict(replica=0),
             at=(2, 3, 4))               # 3 consecutive -> quarantined
    inj.plan('engine_run', 'latency', every=9, latency_s=0.03)
    inj.plan('checkpoint_written', 'corrupt', at=(2,))  # tear the latest

    # ---- a checkpoint dir whose LATEST step is torn ------------------ #
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix='chaos_ckpt_')
    if args.ckpt_dir is None:
        # cleanup must survive ANY exit path — a crashed chaos run
        # must not leak two full param checkpoints into /tmp per run
        atexit.register(shutil.rmtree, ckpt_dir, ignore_errors=True)
    mgr = CheckpointManager(ckpt_dir, fault_injector=inj)
    mgr.save(1, dict(params=swap_params))      # the valid fallback step
    mgr.save(2, dict(params=params))           # torn by the corrupt plan
    print(f'checkpoints: step 1 valid, step 2 TORN (latest) '
          f'in {ckpt_dir}')

    # ---- N replicas, one shared timer, faults wired into every site -- #
    t0 = time.perf_counter()
    timer = PhaseTimer()
    engines = [InferenceEngine(module, params, buckets=buckets,
                               batch_size=args.batch_size, return_type=1,
                               timer=timer, fault_injector=inj)
               for _ in range(args.replicas)]
    print(f'warmup: {args.replicas} replicas x '
          f'{len(engines[0].executables)} bucket executables in '
          f'{time.perf_counter() - t0:.1f}s')
    workers = [ReplicaWorker(i, e, max_wait_ms=args.max_wait_ms,
                             fault_injector=inj)
               for i, e in enumerate(engines)]
    admission = AdmissionController(max_len=buckets[-1],
                                    max_queue_depth=args.max_queue_depth)
    health = HealthConfig(quarantine_after=3, recover_after=2,
                          probe_backoff_s=0.05, probe_backoff_max_s=2.0)
    max_retries = 0 if args.weaken == 'drop' else args.max_retries

    ok = True
    with Router(workers, admission=admission, health=health,
                max_retries=max_retries,
                default_timeout_s=args.timeout_s) as router:
        if args.weaken == 'drop':
            # THE WEAKENED ARM: a fault class becomes droppable — the
            # structured-failure choke point is a no-op, so after-
            # budget failures vanish instead of resolving. The gates
            # below MUST catch this (rc 1) or they are decoration.
            print('WEAKENED GATE ARM: after-budget failures are '
                  'silently dropped (this run must exit 1)')
            router._fail_request = lambda pending, error: None
        logger = MetricLogger(args.metrics, run_meta=dict(
            mode='chaos_smoke', replicas=args.replicas,
            buckets=list(buckets), batch_size=args.batch_size,
            seed=args.seed, weaken=args.weaken,
            dtype=engines[0].dtype_name))
        telemetry = RouterTelemetry(router, admission, logger)
        telemetry.arm()

        rng = np.random.RandomState(args.seed)
        lengths = request_lengths(args, buckets, router.max_len, rng)

        pending, flushed_at, swapped = [], 0, False
        swap_events = []

        def guarded_submit(length, **kw):
            """Every submit path shares the rejection guard: a
            structured RequestRejected (oversize / overload shed) is a
            GATED outcome, never a harness crash — an uncaught one
            would make a crash rc indistinguishable from the zero-lost
            gate firing."""
            tokens = rng.randint(0, cfg.num_tokens, size=length)
            coords = rng.normal(size=(length, 3)).astype(np.float32)
            try:
                pending.append(router.submit(tokens, coords, **kw))
            except RequestRejected as e:
                print(f'rejected: {e.code} {e.detail}')
                logger.log_record('step', mirror=False,
                                  step=len(pending),
                                  rejected=e.to_record())
        for i, length in enumerate(lengths):
            if i == swap_at and not swapped:
                # rolling hot-reload FROM the torn-latest directory:
                # restore_params must fall back to step 1 (the tag
                # names the step it restored)
                swap_events = router.swap_from_checkpoint(ckpt_dir)
                swapped = True
                print(f'rolling swap after request {i}: '
                      f'{len(swap_events)} replicas re-pointed, tag '
                      f'{swap_events[0]["tag"]!r}')
            guarded_submit(length)
            if i in (3, 4):
                # two already-expired requests: must shed BEFORE any
                # dispatch with a structured RequestFailed('deadline')
                guarded_submit(lengths[0], timeout_s=0.0)
            router.pump()
            time.sleep(0.002)   # stream pacing: give probe backoffs
            #                     and latency spikes real time to land
            if router.batches_dispatched - flushed_at >= args.flush_every:
                telemetry.flush()
                flushed_at = router.batches_dispatched
        # keep probing until the quarantined replica recovered (bounded
        # — the breaker must be OBSERVED closing, not assumed)
        probe_rounds = 0
        while router.health.recoveries == 0 and probe_rounds < 200:
            probe_rounds += 1
            time.sleep(0.01)
            guarded_submit(lengths[0])
            router.pump()
        # deadline-drain the stragglers, then close the stream
        while router.queue_depth:
            wait = router.next_deadline()
            if wait:
                time.sleep(wait)
            router.pump()
    # __exit__ -> close(): drained, retries settled, executors down
    telemetry.flush()
    fault_rec = telemetry.fault_flush(injector=inj, pending=pending,
                                      label='chaos_smoke')
    summary = telemetry.close()
    logger.close()

    # ---- gates ------------------------------------------------------- #
    lost = [p.request_id for p in pending if not p.done]
    if lost:
        print(f'FAIL: {len(lost)} submitted requests LOST (resolved '
              f'neither answered nor structured-error): {lost[:10]}')
        ok = False
    unstructured = [p.request_id for p in pending
                    if p.done and p.error is not None
                    and not isinstance(p.error, RequestFailed)]
    if unstructured:
        print(f'FAIL: {len(unstructured)} requests resolved with a RAW '
              f'error instead of a structured RequestFailed: '
              f'{unstructured[:10]}')
        ok = False
    if router.health.recoveries < 1:
        print('FAIL: no quarantine -> recovery transition observed — '
              'the circuit breaker never closed back')
        ok = False
    if len(swap_events) != args.replicas:
        print(f'FAIL: rolling swap incomplete: {len(swap_events)} swap '
              f'events for {args.replicas} replicas')
        ok = False
    elif not swap_events[0]['tag'].endswith('@1'):
        print(f'FAIL: swap restored tag {swap_events[0]["tag"]!r} — '
              f'expected the FALLBACK step 1 (the torn latest step 2 '
              f'must be skipped)')
        ok = False
    if telemetry.post_warmup_compiles:
        print(f'FAIL: {telemetry.post_warmup_compiles} post-warmup '
              f'compile events — injected faults must not break the '
              f'AOT contract')
        ok = False
    by_site = fault_rec['injections_by_site']
    for needed in ('replica_dispatch:exception', 'checkpoint_written:'
                   'corrupt', 'engine_run:latency'):
        if not by_site.get(needed):
            print(f'FAIL: planned fault class {needed!r} never fired — '
                  f'the chaos proved less than it claims')
            ok = False
    if router.timeouts < 2:
        print(f'FAIL: {router.timeouts} deadline timeouts — the two '
              f'timeout_s=0 submits must shed structurally')
        ok = False
    if args.metrics:
        try:
            info = validate_stream(args.metrics)
            print(f'schema ok: {info["records"]} records {info["kinds"]}')
        except SchemaError as e:
            print(f'FAIL: telemetry stream invalid: {e}')
            ok = False

    report = dict(
        ok=ok,
        weaken=args.weaken,
        requests=dict(submitted=len(pending),
                      answered=sum(1 for p in pending if p.ok),
                      structured_failures=sum(
                          1 for p in pending
                          if p.done and p.error is not None),
                      lost=len(lost), **admission.snapshot()),
        injections=fault_rec['injections_by_site'],
        health=router.health.snapshot(),
        health_transitions=router.health.transitions,
        recoveries=router.health.recoveries,
        retries=router.retries,
        request_failures=router.request_failures,
        timeouts=router.timeouts,
        deadline_sheds=router.deadline_sheds,
        swap_tag=swap_events[0]['tag'] if swap_events else None,
        post_warmup_compiles=telemetry.post_warmup_compiles,
        batches=router.batches_dispatched,
        request_latency_ms=summary['metrics']['request_latency_ms'],
    )
    print(json.dumps(report, indent=2, default=str))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(report, f, indent=2, default=str)
        print(f'report -> {args.out}')
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
