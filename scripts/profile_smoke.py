"""Profile-attribution smoke gate (`make profile-smoke`).

Toy run -> jax.profiler trace -> per-scope device-time attribution
(observability.profiling) -> schema-valid `cost` + `profile` records.
Exits non-zero unless:

  * the trace parsed into nonzero device time,
  * the MODEL_SCOPES attribution covers >= --min-coverage of it (the
    proof that the named_scope labels still blanket the hot paths — a
    new unscoped subsystem shows up here as falling coverage, with the
    offending ops named in the record), and
  * the emitted records validate against observability.schema
    (`scripts/obs_report.py --validate --require cost,profile` re-gates
    the stream from the file alone).

Usage:
    python scripts/profile_smoke.py [--metrics STREAM.jsonl]
        [--min-coverage 0.8] [--nodes 64] [--steps 3]
        [--trace-dir DIR] [--train]

Default is the toy model FORWARD (fully under the model scopes);
--train profiles the full train step instead (optimizer/loss ops are
unscoped by design, so expect lower coverage — reported, not gated).
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='toy profile-attribution gate (cost+profile records)')
    ap.add_argument('--metrics', default=None,
                    help='write the schema-valid record stream here')
    ap.add_argument('--min-coverage', type=float, default=0.8)
    ap.add_argument('--nodes', type=int, default=64)
    ap.add_argument('--steps', type=int, default=3)
    ap.add_argument('--trace-dir', default='/tmp/profile_smoke_trace')
    ap.add_argument('--train', action='store_true',
                    help='profile the train step instead of the forward '
                         '(coverage reported, not gated: loss/optimizer '
                         'ops are deliberately outside MODEL_SCOPES)')
    args = ap.parse_args(argv)

    import shutil

    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.observability.costs import cost_payload
    from se3_transformer_tpu.observability.profiling import (
        capture_step_profile, profile_payload,
    )
    from se3_transformer_tpu.observability.report import write_record_stream
    from se3_transformer_tpu.training.denoise import (
        DenoiseConfig, DenoiseTrainer, synthetic_protein_batch,
    )
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()

    cfg = DenoiseConfig(num_nodes=args.nodes, accum_steps=1, num_degrees=2)
    trainer = DenoiseTrainer(cfg)
    batch = synthetic_protein_batch(cfg, trainer.np_rng)
    trainer.init(batch)
    module, params = trainer.module, trainer.params

    if args.train:
        label = f'profile_smoke_train,n={args.nodes}'
        rng = jax.random.PRNGKey(0)
        compiled = trainer._step_fn.lower(
            trainer.params, trainer.opt_state, batch, rng).compile()
        # the step donates params/opt_state (parallel.sharding
        # donate_argnums): each call must re-feed the previous call's
        # outputs or the second dispatch reads deleted buffers
        state = dict(params=trainer.params, opt_state=trainer.opt_state)

        def run():
            out = compiled(state['params'], state['opt_state'], batch, rng)
            state['params'], state['opt_state'] = out[0], out[1]
            return out
    else:
        label = f'profile_smoke_forward,n={args.nodes}'

        def fwd(params, coords):
            return module.apply({'params': params}, batch['seqs'], coords,
                                mask=batch['masks'],
                                adj_mat=batch['adj_mat'], return_type=1)

        compiled = jax.jit(fwd).lower(params, batch['coords']).compile()
        coords = jnp.asarray(np.asarray(batch['coords']))

        def run():
            return compiled(params, coords)

    jax.block_until_ready(run())   # warm (AOT, but first dispatch pays
    #                                buffer setup — keep it out of the
    #                                attributed window)
    hlo_text = compiled.as_text()
    cost = cost_payload(compiled, label=label, hlo_text=hlo_text)

    shutil.rmtree(args.trace_dir, ignore_errors=True)
    capture_step_profile(run, log_dir=args.trace_dir, steps=args.steps)
    profile = profile_payload(
        args.trace_dir, label=label, hlo_text=hlo_text,
        flops_per_step=cost['flops'], steps=args.steps)

    print(json.dumps(dict(label=label,
                          coverage=profile['coverage'],
                          device_time_ms=profile['device_time_ms'],
                          scopes={s: st['share']
                                  for s, st in profile['scopes'].items()},
                          unattributed_top=profile['unattributed_top'][:5],
                          peak_bytes=cost['peak_bytes'],
                          flops=cost['flops'],
                          roofline=profile.get('roofline')), indent=1))

    if args.metrics:
        write_record_stream(
            args.metrics, f'profile_smoke_{os.getpid()}',
            [dict(cost, kind='cost'), dict(profile, kind='profile')])
        print(f'records -> {args.metrics}', file=sys.stderr)

    ok = True
    if not profile['device_time_ms']:
        print('FAIL: trace carried zero device time', file=sys.stderr)
        ok = False
    if not cost['peak_bytes']:
        print('FAIL: cost ledger measured zero peak memory',
              file=sys.stderr)
        ok = False
    if not args.train and profile['coverage'] < args.min_coverage:
        print(f'FAIL: scope attribution covers {profile["coverage"]:.0%} '
              f'of device time < required {args.min_coverage:.0%} — '
              f'hottest unattributed ops: '
              f'{profile["unattributed_top"][:5]}', file=sys.stderr)
        ok = False
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
