#!/bin/bash
# Relaunch tpu_session until it actually gets the chip (rc!=3) — the
# tunnel alternates between blocking (session waits inside) and failing
# init outright (rc=3, needs a fresh process).
#
# CLEAN-SHUTDOWN RULE (VERDICT r3 next #1c): the loop must leave NO
# claim-holding process behind when the builder's round ends, or the
# driver's own bench.py probe wedges on the single-client tunnel.
# `touch /root/repo/.tpu_stop` stops the loop at the next relaunch
# boundary (never mid-session: a running session finishes and releases
# the chip itself; only blocked WAITERS are safe to kill).
cd /root/repo
STOP=/root/repo/.tpu_stop
# a stop file only ever means "stop the CURRENTLY running loop" — a
# stale one from a previous round must not disable this launch.
# (Known, accepted race: a stop touched in the seconds between launch
# and this rm is erased. Protocol: never touch .tpu_stop while also
# launching — see tpu_supervisor.sh header.)
rm -f "$STOP"
while true; do
  if [ -e "$STOP" ]; then
    echo "[loop] stop file present, exiting cleanly $(date -u +%H:%M:%S)" >> /tmp/tpu_session_r2.log
    exit 0
  fi
  python scripts/tpu_session.py /tmp/tpu_session_r2.log
  rc=$?
  echo "[loop] session rc=$rc at $(date -u +%H:%M:%S)" >> /tmp/tpu_session_r2.log
  if [ "$rc" != "3" ]; then exit $rc; fi
  sleep 60
done
