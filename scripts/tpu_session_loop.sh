#!/bin/bash
# Relaunch tpu_session until it actually gets the chip (rc!=3) — the
# tunnel alternates between blocking (session waits inside) and failing
# init outright (rc=3, needs a fresh process).
cd /root/repo
while true; do
  python scripts/tpu_session.py /tmp/tpu_session_r2.log
  rc=$?
  echo "[loop] session rc=$rc at $(date -u +%H:%M:%S)" >> /tmp/tpu_session_r2.log
  if [ "$rc" != "3" ]; then exit $rc; fi
  sleep 60
done
