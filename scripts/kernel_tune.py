"""Block-size tuning sweep for the fused pairwise kernels (on-chip).

The _pick_blocks/_pick_blocks_bx defaults were chosen from a VMEM model,
never from measurement (VERDICT r2 weak #3). This sweep times
fused_pairwise_conv (+ the bx variant) at flagship-relevant shapes
across block settings.

TWO execution modes, auto-detected:
- **in-process** (the tpu_session stage): the axon tunnel is
  SINGLE-CLIENT, so when this process already holds an initialized
  backend, a per-setting subprocess would block at jax init against our
  own claim until its timeout — 16 settings x 1800 s of wedged chip
  (the round-4 near-miss). Instead the sweep flips the env overrides
  in-process and calls `.clear_cache()` on the jit entry points between
  settings, forcing a re-trace that re-reads the env (the jit cache
  keys on shapes/statics, not env).
- **subprocess** (standalone, free tunnel): one child per setting, the
  conservative original design.

Writes crash-safe JSONL.

Usage: python scripts/kernel_tune.py [--out KERNEL_TUNE.jsonl]
       [--iters 30] [--block-e 128 256 512] [--block-if 8 16 32]
"""
import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

CHILD = r'''
import os, sys, time, json
sys.path.insert(0, os.environ['SE3_TPU_REPO'])
import jax, numpy as np, jax.numpy as jnp
from se3_transformer_tpu.utils.compilation_cache import enable_compilation_cache
enable_compilation_cache()
from se3_transformer_tpu.kernels.pallas_pairwise import (
    fused_pairwise_conv, fused_pairwise_conv_bx, fused_pairwise_conv_bxf,
    _pick_blocks, _pick_blocks_bx,
)
kind = os.environ['SE3_TUNE_KIND']
iters = int(os.environ['SE3_TUNE_ITERS'])
rng = np.random.RandomState(0)
# flagship-relevant shape class: E = 1024*32 edges, shared-radial group
# contraction for the widest output degree (dim=64, deg=4 -> IF up to
# 1024, O=64, P=7, mid=128 — the radial trunk width, DEFAULT_MID_DIM;
# the bias is a separate [S, 1] operand since the round-4 un-folding);
# bx: C=64, Q, F up to 7.
# 'bxf' = same contraction fed the flat (p,f,q) basis layout: isolates
# the HBM-operand effect (structured [E,P,Q,F] tile-pads (Q,F)->(8,128),
# ~21x for this shape; flat [E, P*F*Q] pads 343->384).
if kind == 'plain':
    E, mid, IF, O, P = 32768, 128, 1024, 64, 7
    h = jnp.asarray(rng.normal(size=(E, mid)), jnp.float32)
    w3 = jnp.asarray(rng.normal(size=(mid, IF, O)), jnp.float32)
    b3 = jnp.asarray(rng.normal(size=(IF, O)), jnp.float32)
    v2 = jnp.asarray(rng.normal(size=(E, P, IF)), jnp.float32)
    fn = lambda: fused_pairwise_conv(h, w3, v2, b3=b3)
    blocks = _pick_blocks(E, IF, O, P, mid)
else:
    E, mid, C, Q, F, O, P = 32768, 128, 64, 7, 7, 64, 7
    h = jnp.asarray(rng.normal(size=(E, mid)), jnp.float32)
    w3 = jnp.asarray(rng.normal(size=(mid, C * F, O)), jnp.float32)
    b3 = jnp.asarray(rng.normal(size=(C * F, O)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(E, C, Q)), jnp.float32)
    if kind == 'bxf':
        flat = jnp.asarray(rng.normal(size=(E, P * F * Q)), jnp.float32)
        fn = lambda: fused_pairwise_conv_bxf(h, w3, flat, x, (P, Q, F),
                                             b3=b3)
    else:
        bas = jnp.asarray(rng.normal(size=(E, P, Q, F)), jnp.float32)
        fn = lambda: fused_pairwise_conv_bx(h, w3, bas, x, b3=b3)
    blocks = _pick_blocks_bx(E, C, O, P, Q, F, mid)
out = jax.block_until_ready(fn())  # compile
np.asarray(out.ravel()[:1])  # warm the gating fetch (its own tiny program)
t0 = time.time()
for _ in range(iters):
    out = fn()
np.asarray(out.ravel()[:1])  # one-element host fetch gates completion
ms = (time.time() - t0) / iters * 1e3
print(json.dumps(dict(kind=kind, blocks=list(blocks), ms=round(ms, 3),
                      backend=jax.default_backend())))
'''


def _backend_initialized_here() -> bool:
    """True when THIS process already holds an initialized jax backend —
    the single-client tunnel then forbids subprocess children (they
    would block at init against our own claim)."""
    if 'jax' not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:  # noqa: BLE001
        # jax is imported but the private registry moved (jax upgrade):
        # assume HELD — the in-process path is always safe, while a wrong
        # subprocess choice wedges the single-client tunnel 16x1800s
        return True


def _run_inprocess(args, settings):
    import inspect

    import jax
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.kernels import pallas_pairwise as pp
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()
    backend = jax.default_backend()
    rng = np.random.RandomState(0)

    # the loaded kernels may predate the bias un-folding (a long-lived
    # session imports the package once; the sweep then measures the OLD
    # kernel — honest data, flagged in the record)
    has_b3 = 'b3' in inspect.signature(pp.fused_pairwise_conv).parameters
    mid = 128 if has_b3 else 129

    E, IF, O, P = 32768, 1024, 64, 7
    h = jnp.asarray(rng.normal(size=(E, mid)), jnp.float32)
    w3p = jnp.asarray(rng.normal(size=(mid, IF, O)), jnp.float32)
    b3p = jnp.asarray(rng.normal(size=(IF, O)), jnp.float32)
    v2 = jnp.asarray(rng.normal(size=(E, P, IF)), jnp.float32)
    C, Q, F = 64, 7, 7
    w3x = jnp.asarray(rng.normal(size=(mid, C * F, O)), jnp.float32)
    b3x = jnp.asarray(rng.normal(size=(C * F, O)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(E, C, Q)), jnp.float32)
    bas = jnp.asarray(rng.normal(size=(E, P, Q, F)), jnp.float32)
    flat = jnp.asarray(rng.normal(size=(E, P * F * Q)), jnp.float32)

    def fns(kind):
        kwp = {'b3': b3p} if has_b3 else {}
        kwx = {'b3': b3x} if has_b3 else {}
        pick_bx = lambda: pp._pick_blocks_bx(E, C, O, P, Q, F, mid)  # noqa: E731,E501
        if kind == 'plain':
            return (lambda: pp.fused_pairwise_conv(h, w3p, v2, **kwp),
                    lambda: pp._pick_blocks(E, IF, O, P, mid))
        if kind == 'bxf':
            return (lambda: pp.fused_pairwise_conv_bxf(
                h, w3x, flat, x, (P, Q, F), **kwx), pick_bx)
        return (lambda: pp.fused_pairwise_conv_bx(h, w3x, bas, x, **kwx),
                pick_bx)

    def clear_caches():
        # getattr by name: an old loaded package may predate some entry
        # points (e.g. bxf landed in 3c1c681) — missing ones are skipped,
        # not an AttributeError that voids every setting
        for name in ('fused_pairwise_conv', 'fused_pairwise_conv_bx',
                     'fused_pairwise_conv_bxf'):
            f = getattr(pp, name, None)
            if f is not None and hasattr(f, 'clear_cache'):
                f.clear_cache()

    for kind, env_blocks in settings:
        rec = dict(kind=kind, mode='in-process', bias_unfolded=has_b3,
                   **env_blocks)
        saved = {k: os.environ.pop(k) for k in list(os.environ)
                 if k.startswith('SE3_TPU_BLOCK_')}
        os.environ.update(env_blocks)
        try:
            clear_caches()
            fn, pick = fns(kind)
            rec['blocks'] = list(pick())
            t_c = time.time()
            out = jax.block_until_ready(fn())  # compile
            rec['compile_s'] = round(time.time() - t_c, 1)
            np.asarray(out.ravel()[:1])  # warm the gating fetch
            t0 = time.time()
            for _ in range(args.iters):
                out = fn()
            np.asarray(out.ravel()[:1])  # one-element fetch gates completion
            rec['ms'] = round((time.time() - t0) / args.iters * 1e3, 3)
            rec['backend'] = backend
        except Exception as e:  # noqa: BLE001 - isolate per setting
            from se3_transformer_tpu.utils.helpers import is_tunnel_error
            msg = f'{type(e).__name__}: {e}'
            # shared classifier: an aggressive block setting that OOMs
            # must be recorded for this setting and the sweep continue —
            # only true tunnel deaths (OOMs carved out) abort the sweep
            if is_tunnel_error(msg):
                raise  # tunnel death: retryable, do not record as data
            rec['error'] = msg[:300]
        finally:
            for k in env_blocks:
                os.environ.pop(k, None)
            os.environ.update(saved)
        print(json.dumps(rec), flush=True)
        with open(args.out, 'a') as f:
            f.write(json.dumps(rec) + '\n')


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default=os.path.join(REPO, 'KERNEL_TUNE.jsonl'))
    ap.add_argument('--iters', type=int, default=30)
    ap.add_argument('--block-e', type=int, nargs='+',
                    default=[0, 128, 256, 512])  # 0 = heuristic default
    ap.add_argument('--block-if', type=int, nargs='+', default=[8, 16, 32])
    ap.add_argument('--block-cb', type=int, nargs='+', default=[8, 16])
    args = ap.parse_args(argv)

    settings = []
    for kind, sizes_key, sizes in (('plain', 'SE3_TPU_BLOCK_IF',
                                    args.block_if),
                                   ('bx', 'SE3_TPU_BLOCK_CB',
                                    args.block_cb),
                                   ('bxf', 'SE3_TPU_BLOCK_CB',
                                    args.block_cb)):
        settings.append((kind, {}))  # heuristic default: baseline to beat
        for be in args.block_e:
            if be == 0:
                continue
            for bs in sizes:
                settings.append((kind, {'SE3_TPU_BLOCK_E': str(be),
                                        sizes_key: str(bs)}))

    if _backend_initialized_here():
        return _run_inprocess(args, settings)

    child = os.path.join('/tmp', 'kernel_tune_child.py')
    with open(child, 'w') as f:
        f.write(CHILD)

    def run(kind, env_blocks):
        # strip stale overrides so the {}-baseline really times the
        # heuristic even if the operator has the knobs exported
        base = {k: v for k, v in os.environ.items()
                if not k.startswith('SE3_TPU_BLOCK_')}
        env = dict(base, SE3_TPU_REPO=REPO, SE3_TUNE_KIND=kind,
                   SE3_TUNE_ITERS=str(args.iters), **env_blocks)
        rec = dict(kind=kind, **{k: v for k, v in env_blocks.items()})
        try:
            p = subprocess.run([sys.executable, child], env=env,
                               capture_output=True, text=True, timeout=1800)
        except subprocess.TimeoutExpired:
            rec['error'] = 'timeout (1800s) — compile hang or wedged tunnel'
            p = None
        if p is not None:
            lines = [l for l in p.stdout.splitlines() if l.startswith('{')]
            if p.returncode == 0 and lines:
                rec.update(json.loads(lines[-1]))
            else:
                rec['error'] = (p.stderr.strip()[-300:] or
                                f'rc={p.returncode}')
        print(json.dumps(rec), flush=True)
        with open(args.out, 'a') as f:
            f.write(json.dumps(rec) + '\n')
        return rec

    for kind, env_blocks in settings:
        run(kind, env_blocks)


if __name__ == '__main__':
    main()
