"""Fleet transport A/B loadgen (`make transport-smoke`).

The ISSUE 20 acceptance harness for the binary RPC arm: the SAME
seeded closed-loop workload (C client threads, N `infer` calls against
a real `HostServer` + `Router` over a fake engine — no compiles, so
the WIRE is the variable) is driven through both transports:

  legacy — `serve_socket` + `SocketTransport`: connect-per-call,
           newline-JSON, arrays degraded to lists at the wire.
  binary — `serve_binary` + `BinaryTransport`: persistent pooled
           connections, correlation-id multiplexing, length-prefixed
           frames with raw dtype/shape-tagged array segments (zero
           tolist/json on the array path).

Per arm: QPS (closed-loop wall clock), p50/p99 request latency, and
bytes-on-wire per call off the transport's own counters. The verdict
rides ONE schema'd `transport` record banked to TRANSPORT_AB.jsonl —
`qps_binary_vs_legacy` (floor 3x), `p99_binary_vs_legacy` (ceiling),
`wire_bytes_binary_vs_legacy` (ceiling) — judged by the committed
PERF_BUDGETS.json entries via scripts/perf_gate.py, with the
qualitative invariants (zero errors, zero frame errors, zero
mid-workload reconnects, in-flight depth actually > 1) gated by
`obs_report --require transport`.

`--inject-regression` writes a corrupted record (QPS win gone, p99
blown, wire FATTER than JSON) and requires perf_gate.py to FIRE on
it, then exits 1 — proving the budgets bite (the Makefile asserts
rc==1).

    python scripts/transport_loadgen.py [--metrics TRANSPORT_AB.jsonl]
        [--requests 240] [--concurrency 8] [--length 768] [--seed 0]
"""
import argparse
import json
import os
import subprocess
import sys
import threading
import time
import uuid

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


class _WireBoundEngine:
    """Engine-shaped stand-in (no jax, no compiles): answers instantly
    so the A/B isolates the transport — serialization, connection
    setup, and framing are the only costs that differ between arms."""

    def __init__(self, buckets, batch_size=2):
        self.buckets = tuple(buckets)
        self.batch_size = batch_size
        self.rows_served = {b: 0 for b in self.buckets}
        self.params = 'v0'
        self.executables = {}
        self.cost_payloads = {}
        from se3_transformer_tpu.observability import PhaseTimer
        self.timer = PhaseTimer()

    def run(self, bucket, tokens, coords, mask):
        self.rows_served[bucket] += int(np.asarray(mask).any(-1).sum())
        with self.timer.phase(f'bucket_{bucket}'):
            pass
        return np.broadcast_to(
            np.arange(tokens.shape[1], dtype=np.float32)[None, :, None],
            tokens.shape + (3,)).copy()


def _build_host(length, batch_size=2):
    from se3_transformer_tpu.inference import AdmissionController
    from se3_transformer_tpu.serving import (
        HostServer, ReplicaWorker, Router,
    )
    engine = _WireBoundEngine((length,), batch_size)
    worker = ReplicaWorker(0, engine, max_wait_ms=1.0)
    router = Router([worker],
                    admission=AdmissionController(max_len=length),
                    max_retries=1)
    return HostServer(router, host_id=0)


def _workload(n, length, seed):
    """Pre-generated seeded requests — identical arrays hit both arms,
    sized so array serialization dominates the envelope."""
    rng = np.random.RandomState(seed)
    reqs = []
    for _ in range(n):
        ln = int(rng.randint(max(length // 2, length - 128), length + 1))
        reqs.append((rng.randint(0, 32, size=ln).astype(np.int32),
                     rng.normal(size=(ln, 3)).astype(np.float32)))
    return reqs


def run_arm(name, transport, requests, concurrency, timeout_s=30.0):
    """Closed-loop: C threads race through the shared request list;
    every response is shape-checked so a transport that corrupts the
    array path cannot win on speed."""
    lock = threading.Lock()
    latencies, failures = [], []
    cursor = [0]

    def client(tid):
        while True:
            with lock:
                i = cursor[0]
                if i >= len(requests):
                    return
                cursor[0] += 1
            tokens, coords = requests[i]
            t0 = time.perf_counter()
            try:
                resp = transport.call(
                    'infer',
                    dict(tokens=tokens, coords=coords,
                         timeout_s=timeout_s),
                    timeout_s=timeout_s)
                if not resp.get('ok'):
                    raise RuntimeError(f'structured failure: '
                                       f'{resp.get("error")}')
                result = np.asarray(resp['result'])
                if result.shape != (len(tokens), 3):
                    raise RuntimeError(
                        f'result shape {result.shape} != '
                        f'({len(tokens)}, 3)')
            except Exception as e:  # noqa: BLE001
                with lock:
                    failures.append(f'{name}[t{tid} req{i}]: {e}')
                continue
            ms = (time.perf_counter() - t0) * 1e3
            with lock:
                latencies.append(ms)

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(t,), daemon=True)
               for t in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start

    stats = transport.transport_stats()
    lat = sorted(latencies)

    def pct(p):
        return round(lat[min(len(lat) - 1,
                             int(p / 100.0 * len(lat)))], 3) if lat else 0.0

    wire = stats['bytes_sent'] + stats['bytes_received']
    arm = dict(
        requests=len(latencies),
        errors=len(failures),
        qps=round(len(latencies) / max(wall_s, 1e-9), 2),
        p50_ms=pct(50), p99_ms=pct(99),
        bytes_per_call=int(wire / max(len(latencies), 1)),
        wall_s=round(wall_s, 3),
        transport=stats,
    )
    for f in failures[:5]:
        print(f'  ERROR {f}')
    print(f'{name:>6}: {arm["requests"]} ok / {arm["errors"]} err, '
          f'{arm["qps"]} qps, p50 {arm["p50_ms"]}ms p99 {arm["p99_ms"]}ms, '
          f'{arm["bytes_per_call"]} B/call '
          f'(conns {stats["connections_opened"]}, '
          f'peak in-flight {stats["peak_in_flight"]}, '
          f'frame errors {stats["frame_errors"]})')
    return arm


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='transport A/B: legacy connect-per-call JSON vs '
                    'pooled multiplexed binary framing, same seeded '
                    'workload')
    ap.add_argument('--metrics', default=None,
                    help='bank the schema-valid transport stream here')
    ap.add_argument('--requests', type=int, default=240)
    ap.add_argument('--concurrency', type=int, default=8)
    ap.add_argument('--length', type=int, default=768,
                    help='engine bucket / max token length — sized so '
                         'array bytes dominate the control envelope')
    ap.add_argument('--pool-size', type=int, default=2,
                    help='binary arm: pooled connections per client')
    ap.add_argument('--seed', type=int, default=0)
    ap.add_argument('--inject-regression', action='store_true',
                    help='write a corrupted record and require the '
                         'perf gate to fire on it (exits 1 when it '
                         'does)')
    args = ap.parse_args(argv)

    run_id = f'transport_loadgen_{uuid.uuid4().hex[:8]}'
    if args.inject_regression:
        return inject_regression(args, run_id)

    from se3_transformer_tpu.serving import (
        BinaryTransport, SocketTransport, serve_binary, serve_socket,
    )

    requests = _workload(args.requests, args.length, args.seed)
    ok = True
    arms = {}

    # ---- legacy arm: connect-per-call newline-JSON ----------------- #
    host = _build_host(args.length)
    sock = serve_socket(host, port=0)
    legacy = SocketTransport('127.0.0.1', sock.port, label='ab-legacy')
    try:
        arms['legacy'] = run_arm('legacy', legacy, requests,
                                 args.concurrency)
    finally:
        sock.close()
        host.stop()

    # ---- binary arm: pooled + multiplexed + raw array frames ------- #
    host = _build_host(args.length)
    srv = serve_binary(host, port=0)
    binary = BinaryTransport('127.0.0.1', srv.port, label='ab-binary',
                             pool_size=args.pool_size)
    try:
        arms['binary'] = run_arm('binary', binary, requests,
                                 args.concurrency)
        server_stats = srv.transport_stats()
    finally:
        binary.close()
        srv.close()
        host.stop()

    for name, arm in arms.items():
        if arm['errors'] or arm['requests'] != args.requests:
            print(f'FAIL: {name} arm answered {arm["requests"]}/'
                  f'{args.requests} with {arm["errors"]} errors')
            ok = False
    bstats = arms['binary']['transport']
    if bstats['frame_errors'] or server_stats['frame_errors']:
        print(f'FAIL: frame errors on a clean run (client '
              f'{bstats["frame_errors"]}, server '
              f'{server_stats["frame_errors"]})')
        ok = False
    if bstats['reconnects']:
        print(f'FAIL: {bstats["reconnects"]} reconnects with no host '
              f'restart — connections are not persisting')
        ok = False
    if bstats['peak_in_flight'] < 2:
        print('FAIL: binary peak in-flight < 2 — nothing multiplexed')
        ok = False

    def ratio(field):
        b, l = arms['binary'][field], arms['legacy'][field]
        return round(b / max(l, 1e-9), 3)

    ratios = dict(
        qps_binary_vs_legacy=ratio('qps'),
        p99_binary_vs_legacy=ratio('p99_ms'),
        wire_bytes_binary_vs_legacy=ratio('bytes_per_call'),
    )
    print(f'binary vs legacy: {ratios["qps_binary_vs_legacy"]}x QPS, '
          f'{ratios["p99_binary_vs_legacy"]}x p99, '
          f'{ratios["wire_bytes_binary_vs_legacy"]}x wire bytes '
          f'(floors/ceilings enforced by scripts/perf_gate.py)')

    if args.metrics:
        from se3_transformer_tpu.observability.report import (
            write_record_stream,
        )
        from se3_transformer_tpu.observability.schema import (
            validate_stream,
        )
        body = dict(
            kind='transport',
            label=f'loadgen,n={args.requests},c={args.concurrency},'
                  f'len={args.length}',
            workload=dict(requests=args.requests,
                          concurrency=args.concurrency,
                          length=args.length, seed=args.seed,
                          pool_size=args.pool_size),
            arms={name: {k: v for k, v in arm.items()
                         if k != 'transport'}
                  for name, arm in arms.items()},
            transport=bstats,
            server_transport=server_stats,
            **ratios)
        write_record_stream(args.metrics, run_id, [body])
        info = validate_stream(args.metrics)
        print(f'schema ok: {info["records"]} records {info["kinds"]}')

    print(json.dumps(dict(ok=ok, **ratios)))
    return 0 if ok else 1


def inject_regression(args, run_id):
    """Write a corrupted transport record and require the committed
    budgets to fire. Exits 1 when the gate bites (the Makefile asserts
    exactly that), 2 when the corruption goes UNDETECTED."""
    assert args.metrics, '--inject-regression needs --metrics'
    from se3_transformer_tpu.observability.report import (
        write_record_stream,
    )
    dead = dict(requests=args.requests, errors=0, qps=100.0,
                p50_ms=5.0, p99_ms=20.0, bytes_per_call=40000)
    body = dict(
        kind='transport', label='loadgen,INJECTED',
        workload=dict(requests=args.requests,
                      concurrency=args.concurrency,
                      length=args.length, seed=args.seed,
                      pool_size=args.pool_size),
        arms=dict(legacy=dict(dead), binary=dict(dead, p99_ms=200.0)),
        transport=dict(connections_opened=2, reconnects=0,
                       peak_in_flight=8, bytes_sent=1, bytes_received=1,
                       frame_errors=0),
        # the three regressions the budgets exist to catch: the QPS
        # win gone, p99 blown past JSON, and a wire FATTER than JSON
        qps_binary_vs_legacy=1.0,
        p99_binary_vs_legacy=10.0,
        wire_bytes_binary_vs_legacy=2.0)
    write_record_stream(args.metrics, run_id, [body])
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, 'perf_gate.py'),
         args.metrics],
        capture_output=True, text=True, cwd=REPO)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    if proc.returncode == 0:
        print('INJECTED REGRESSION NOT CAUGHT: perf_gate passed a '
              'record with QPS ratio 1.0, p99 ratio 10.0, and wire '
              'ratio 2.0 — the transport budgets are not wired')
        return 2
    print('perf gate FIRED on the injected transport regression '
          f'(rc={proc.returncode}) — budgets are live')
    return 1


if __name__ == '__main__':
    sys.exit(main())
