"""First-class kernel block-size autotuner (END-TO-END, shape-keyed).

Supersedes the retired scripts/kernel_tune.py subprocess-per-env-var
sweep. That sweep timed STANDALONE kernels — and its rankings were
measured OPPOSITE to end-to-end rankings (flipping the picker from its
standalone winner cost the production conservative flagship 2.7x,
294.97 -> 107.51 nodes*steps/s, commit d0cd10d / BENCH_SESSION.jsonl).
This tuner therefore never times a kernel in isolation:

  1. build the REAL bench-style train step (recipes + synthetic batch +
     make_sharded_train_step — the program the records are made of) and
     trace it once: the kernels' pick functions record every
     (kind, shape, dtype) they resolved — those are the tuning targets;
  2. per target, enumerate only tile-legal, VMEM-model-admissible
     candidates (kernels.tuning.admissible_candidates — the bwd-aware
     admission that excludes up front the bx/bxf (512, 16) / bx
     (256, 16) Mosaic VMEM compile failures the old sweep paid for,
     KERNEL_TUNE.jsonl);
  3. measure each candidate through the full train step in ALTERNATING
     A/B pairs against the incumbent (tunnel-latency noise is one-sided
     and time-correlated; alternation is the round-4/5 session
     estimator), via `tuning.force(...)` — an in-process pending table
     entry, no subprocess and no env-string round-trip;
  4. promote into the persistent shape-keyed cache (kernels/tuning.py)
     only a candidate that beats the incumbent BY the noise margin in
     EVERY alternating pair;
  5. prove adoption: re-trace the step and require the promoted entry to
     resolve from the cache (`consulted` verdict) — exit non-zero
     otherwise.

Every step emits a schema'd `tune` JSONL record
(observability/schema.py; crash-safe append). `make tune-smoke` runs
the interpret-mode CPU mini-sweep; on chip, run inside a tpu_session
stage (the axon tunnel is single-client — this tuner is in-process by
construction, so it cannot deadlock against its own claim the way the
subprocess design nearly did).

Usage:
    python scripts/tune_kernels.py [--dry-run] [--smoke]
        [--out TUNE.jsonl] [--steps 10] [--pairs 3] [--margin 0.03]
        [--recipe flagship_fast] [--kinds plain bx bxf attention]
        [--max-candidates 0] [--fuse-basis]

--margin is the fractional end-to-end win a candidate must clear; the
default 0.03 sits above the observed same-session window spread
(~1-2%). A non-positive margin still measures end-to-end (never the
standalone kernel) — `make tune-smoke` uses it to exercise the
promotion/consult machinery deterministically on CPU.
"""
import argparse
import json
import os
import sys
import time
import uuid

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)


def _emit(args, rec):
    """Schema-validate, then crash-safe append + mirror to stdout."""
    from se3_transformer_tpu.observability.schema import validate_record
    validate_record(rec)
    line = json.dumps(rec)
    print(line, flush=True)
    with open(args.out, 'a') as f:
        f.write(line + '\n')
        f.flush()


def _build_step(args):
    """The real bench-style program: module + synthetic batch + sharded
    train step factory. Returns (make_step, state) where make_step()
    hands back a FRESH jitted step (each candidate must re-trace so the
    pick functions re-run) and state carries params/opt_state/data."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from se3_transformer_tpu.models.se3_transformer import (
        SE3TransformerModule,
    )
    from se3_transformer_tpu.parallel.sharding import (
        make_sharded_train_step,
    )
    from se3_transformer_tpu.training import recipes

    if args.smoke:
        # interpret-mode toy: same program shape as the CPU liveness
        # bench, with the Pallas kernels forced through the interpreter
        # so the pick functions actually resolve on CPU.
        # --conv-backend so2 traces the banded SO(2) path instead, so
        # the 'so2' kind's streaming chunks become tuning targets
        num_nodes, dim = args.nodes or 32, 8
        # --fuse-pairwise routes the attention blocks through the
        # streaming flash kernel (interpret mode), so the 'flash' /
        # 'flash_stream' kinds become tuning targets; --pallas-attention
        # enables the per-degree fused attention kernel so 'attention'
        # AND 'attention_bwd' picks resolve in the traced train step
        if args.attention_mode == 'global':
            # the kNN-free step: the XLA streaming dispatch consults
            # the 'flash_global' chunk kind directly on CPU (pallas
            # off — the global kernel's stream fallback IS the CPU
            # path), no interpret-mode kernels needed
            assert not (args.fuse_pairwise or args.fuse_basis
                        or args.pallas_attention), \
                '--attention-mode global subsumes the fused-attention ' \
                'flags (the global path always streams)'
            module = SE3TransformerModule(
                num_tokens=24, dim=dim, dim_head=8, heads=2, depth=1,
                attend_self=True, input_degrees=1, num_degrees=2,
                output_degrees=2, reduce_dim_out=True,
                differentiable_coors=True, pallas=False,
                attention_mode='global',
                conv_backend=args.conv_backend)
            label = f'smoke,dim={dim},global,{args.conv_backend}'
        else:
            module = SE3TransformerModule(
                num_tokens=24, dim=dim, dim_head=8, heads=2, depth=1,
                attend_self=True, input_degrees=1, num_degrees=2,
                output_degrees=2, reduce_dim_out=True,
                differentiable_coors=True, num_neighbors=8,
                pallas=True, pallas_interpret=True,
                fuse_basis=args.fuse_basis,
                fuse_pairwise=args.fuse_pairwise,
                flash_interpret=args.fuse_pairwise,
                shared_radial_hidden=args.fuse_pairwise,
                pallas_attention=args.pallas_attention or None,
                pallas_attention_interpret=args.pallas_attention,
                conv_backend=args.conv_backend)
            label = f'smoke,dim={dim},interpret,{args.conv_backend}'
    else:
        num_nodes = args.nodes or 1024
        overrides = dict(output_degrees=2, reduce_dim_out=True)
        if args.fuse_pairwise:
            overrides.update(fuse_pairwise=True,
                             shared_radial_hidden=True)
        if args.pallas_attention:
            overrides['pallas_attention'] = True
        if args.attention_mode == 'global':
            overrides['attention_mode'] = 'global'
        module = recipes.RECIPES[args.recipe](dim=args.dim, **overrides)
        label = f'{args.recipe},dim={args.dim}'

    rng = np.random.RandomState(0)
    if args.smoke:
        seqs = jnp.asarray(rng.randint(0, 24, (1, num_nodes)))
    else:
        seqs = jnp.asarray(rng.normal(size=(1, num_nodes, args.dim)),
                           jnp.float32)
    coords = jnp.asarray(np.cumsum(
        rng.normal(size=(1, num_nodes, 3)), axis=1), jnp.float32)
    coords = coords - coords.mean(axis=1, keepdims=True)
    data = dict(seqs=seqs, coords=coords,
                masks=jnp.ones((1, num_nodes), bool))

    def loss_fn(params, batch, key):
        noise = jax.random.normal(key, batch['coords'].shape,
                                  batch['coords'].dtype)
        noised = batch['coords'] + noise
        out = module.apply({'params': params}, batch['seqs'], noised,
                           mask=batch['masks'], return_type=1)
        loss = (((noised + out) - batch['coords']) ** 2).sum(-1).mean()
        return loss, dict()

    init_fn = jax.jit(module.init, static_argnames=('return_type',))
    params = init_fn(jax.random.PRNGKey(0), seqs, coords,
                     mask=data['masks'], return_type=1)['params']
    optimizer = optax.adam(1e-4)
    state = dict(params=params, opt_state=optimizer.init(params),
                 data=data, key=jax.random.PRNGKey(1),
                 num_nodes=num_nodes, label=label)

    def make_step():
        return make_sharded_train_step(loss_fn, optimizer)

    return make_step, state


def _measure_window(step, state, steps):
    """One timed end-to-end window; returns nodes*steps/sec. Same
    close-the-clock semantics as bench.py: the tail is host-fetched
    before the clock stops."""
    import jax
    t0 = time.monotonic()
    params, opt_state = state['params'], state['opt_state']
    key, data = state['key'], state['data']
    loss = None
    for _ in range(steps):
        key, sub = jax.random.split(key)
        params, opt_state, loss, _ = step(params, opt_state, data, sub)
    float(loss)
    jax.block_until_ready(params)
    dt = time.monotonic() - t0
    state.update(params=params, opt_state=opt_state, key=key)
    return state['num_nodes'] * steps / dt


def _targets_from_trace(make_step, state, kinds):
    """Lower (trace-only, no backend compile) a fresh step and read the
    pick-function consult log: the (kind, shape, dtype) tuples the real
    program resolves are the tuning targets."""
    from se3_transformer_tpu.kernels import tuning
    tuning.clear_kernel_caches()
    tuning.reset_consults()
    step = make_step()
    step.lower(state['params'], state['opt_state'], state['data'],
               state['key'])
    targets = []
    seen = set()
    for c in tuning.consults():
        key = (c['kernel'], tuple(c['shape']), c['dtype'])
        if c['kernel'] in kinds and key not in seen:
            seen.add(key)
            targets.append(dict(kernel=c['kernel'], shape=list(c['shape']),
                                dtype=c['dtype'], source=c['source'],
                                blocks=c['blocks']))
    return targets


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='end-to-end shape-keyed kernel block autotuner')
    ap.add_argument('--out', default=os.path.join(REPO, 'TUNE.jsonl'))
    ap.add_argument('--dry-run', action='store_true',
                    help='enumerate admissible candidates and emit tune '
                         'records without measuring or promoting')
    ap.add_argument('--smoke', action='store_true',
                    help='interpret-mode CPU mini-sweep (make tune-smoke)')
    ap.add_argument('--steps', type=int, default=10,
                    help='train steps per timed window')
    ap.add_argument('--pairs', type=int, default=3,
                    help='alternating incumbent/candidate window pairs')
    ap.add_argument('--margin', type=float, default=0.03,
                    help='fractional end-to-end win required to promote')
    ap.add_argument('--recipe', default='flagship_fast')
    ap.add_argument('--dim', type=int, default=64)
    ap.add_argument('--nodes', type=int, default=0)
    ap.add_argument('--kinds', nargs='+',
                    default=['plain', 'bx', 'bxf', 'attention',
                             'attention_bwd', 'so2', 'flash',
                             'flash_stream', 'flash_global'])
    ap.add_argument('--conv-backend', default='dense',
                    help="smoke module's conv backend ('dense'|'so2');"
                         " 'so2' makes the banded contraction's chunk "
                         "count a tuning target")
    ap.add_argument('--max-candidates', type=int, default=0,
                    help='per target; 0 = all admissible')
    ap.add_argument('--max-targets', type=int, default=0,
                    help='tune only the first N discovered targets; '
                         '0 = all (the smoke gate bounds its runtime '
                         'with this — interpret-mode compiles are slow)')
    ap.add_argument('--fuse-basis', action='store_true',
                    help='smoke: exercise the bx/bxf kinds instead of '
                         'plain')
    ap.add_argument('--fuse-pairwise', action='store_true',
                    help='route attention through the streaming flash '
                         'kernel so the flash/flash_stream kinds become '
                         'tuning targets (implies shared_radial_hidden)')
    ap.add_argument('--pallas-attention', action='store_true',
                    help='enable the per-degree fused attention kernel '
                         "so the 'attention' and 'attention_bwd' kinds "
                         'become tuning targets')
    ap.add_argument('--attention-mode', default='knn',
                    choices=('knn', 'global'),
                    help="'global' traces the kNN-free large-assembly "
                         "step so the 'flash_global' stream-chunk kind "
                         'becomes a tuning target')
    args = ap.parse_args(argv)

    if args.smoke:
        os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import jax
    if args.smoke:
        try:
            jax.config.update('jax_platforms', 'cpu')
        except Exception:  # noqa: BLE001 - already pinned via env
            pass

    from se3_transformer_tpu.kernels import tuning
    from se3_transformer_tpu.observability import collect_run_meta

    run_id = f'tune-{uuid.uuid4().hex[:12]}'
    meta = collect_run_meta(extra=dict(
        tool='tune_kernels', mode='smoke' if args.smoke else 'full',
        dry_run=args.dry_run, margin=args.margin, pairs=args.pairs,
        steps=args.steps, cache_file=tuning.cache_file()))
    meta['run_id'] = run_id
    _emit(args, meta)

    make_step, state = _build_step(args)
    targets = _targets_from_trace(make_step, state, set(args.kinds))
    if not targets:
        print('no tunable kernel picks resolved in this program '
              '(is the Pallas path enabled?)', file=sys.stderr)
        return 1
    if args.max_targets > 0:
        targets = targets[:args.max_targets]
    device_kind = tuning.current_device_kind()

    promoted_entries = {}  # (kernel, shape, dtype) -> entry; promote()
    # overwrites by key, so only the LAST winner per target is verifiable
    failures = 0
    for tgt in targets:
        kind, shape, dtype = tgt['kernel'], tgt['shape'], tgt['dtype']
        incumbent = tuple(tgt['blocks'])
        cands = [c for c in tuning.admissible_candidates(kind, shape)
                 if c != incumbent]
        if args.max_candidates > 0:
            cands = cands[:args.max_candidates]
        print(f'target {kind}{tuple(shape)} dtype={dtype}: incumbent '
              f'{incumbent} ({tgt["source"]}), {len(cands)} candidates',
              file=sys.stderr)
        if args.dry_run:
            for cand in cands:
                _emit(args, dict(
                    kind='tune', run_id=run_id, kernel=kind, shape=shape,
                    dtype=dtype, candidate=list(cand),
                    incumbent=list(incumbent), blocks=list(incumbent),
                    step_ms=None, verdict='admitted', promoted=False))
            continue

        # incumbent arm: fresh trace at the current pick (cache entry if
        # one is already promoted, else heuristic)
        tuning.clear_kernel_caches()
        step_inc = make_step()
        _measure_window(step_inc, state, 1)  # compile outside the clock
        for cand in cands:
            # shape+dtype pinned: the candidate steers ONLY the target
            # pick — other same-kind shapes in the program keep their
            # deployed resolution, so the A/B measures the program that
            # will actually run after promotion
            with tuning.force(kind, cand, shape=shape, dtype=dtype):
                step_cand = make_step()
                try:
                    _measure_window(step_cand, state, 1)  # compile
                except Exception as e:  # noqa: BLE001 - isolate per
                    # candidate: a Mosaic VMEM reject the model missed
                    # must be recorded, not abort the sweep. Tunnel /
                    # infrastructure deaths are NOT candidate data —
                    # re-raise so the session retry machinery sees them
                    # instead of measuring every remaining candidate
                    # against a dead tunnel
                    from se3_transformer_tpu.utils.helpers import (
                        is_tunnel_error,
                    )
                    if is_tunnel_error(str(e)):
                        raise
                    _emit(args, dict(
                        kind='tune', run_id=run_id, kernel=kind,
                        shape=shape, dtype=dtype, candidate=list(cand),
                        incumbent=list(incumbent),
                        blocks=list(incumbent), step_ms=None,
                        verdict='error', promoted=False,
                        error=f'{type(e).__name__}: {e}'[:300]))
                    failures += 1
                    continue
                pairs = []
                for _ in range(max(1, args.pairs)):
                    r_inc = _measure_window(step_inc, state, args.steps)
                    r_cand = _measure_window(step_cand, state, args.steps)
                    pairs.append(dict(incumbent=round(r_inc, 2),
                                      candidate=round(r_cand, 2)))
            inc_best = max(p['incumbent'] for p in pairs)
            cand_best = max(p['candidate'] for p in pairs)
            # the promotion rule, verbatim from the measured history: the
            # candidate must beat the incumbent BY THE NOISE MARGIN in
            # EVERY alternating pair — a single lost pair under the
            # one-sided tunnel noise means the direction is not proven
            wins_all = all(p['candidate'] > p['incumbent'] *
                           (1.0 + args.margin) for p in pairs)
            verdict = 'promoted' if wins_all else 'rejected'
            rec = dict(
                kind='tune', run_id=run_id, kernel=kind, shape=shape,
                dtype=dtype, candidate=list(cand),
                incumbent=list(incumbent),
                blocks=list(cand if verdict == 'promoted' else incumbent),
                # rate = nodes*steps/dt, so dt/steps = nodes/rate
                step_ms=round(state['num_nodes'] / cand_best * 1e3, 3),
                nodes_steps_per_sec=cand_best,
                incumbent_nodes_steps_per_sec=inc_best,
                pairs=pairs, margin=args.margin,
                verdict=verdict, promoted=verdict == 'promoted')
            if verdict == 'promoted':
                tuning.promote(
                    kind, shape, cand, dtype=dtype,
                    device_kind=device_kind,
                    provenance=dict(
                        benched_nodes_steps_per_sec=cand_best,
                        incumbent_nodes_steps_per_sec=inc_best,
                        incumbent_blocks=list(incumbent),
                        pairs=pairs, steps_per_window=args.steps,
                        margin=args.margin, label=state['label'],
                        run_id=run_id))
                promoted_entries[(kind, tuple(shape), dtype)] = \
                    dict(kernel=kind, shape=shape, dtype=dtype,
                         blocks=list(cand))
                # the new entry is the incumbent for later candidates
                incumbent = tuple(cand)
                tuning.clear_kernel_caches()
                step_inc = make_step()
                _measure_window(step_inc, state, 1)
            _emit(args, rec)

    # prove adoption: a fresh trace must resolve every promoted entry
    # from the cache — the `make tune-smoke` gate rides this verdict
    if promoted_entries:
        tuning.clear_kernel_caches()
        tuning.reset_consults()
        step = make_step()
        step.lower(state['params'], state['opt_state'], state['data'],
                   state['key'])
        resolved = {(c['kernel'], tuple(c['shape']), c['dtype']):
                    (c['source'], tuple(c['blocks']))
                    for c in tuning.consults()}
        for ent in promoted_entries.values():
            got = resolved.get(
                (ent['kernel'], tuple(ent['shape']), ent['dtype']))
            ok = got is not None and got[0] == 'cache' \
                and got[1] == tuple(ent['blocks'])
            _emit(args, dict(
                kind='tune', run_id=run_id, kernel=ent['kernel'],
                shape=ent['shape'], dtype=ent['dtype'],
                candidate=ent['blocks'], blocks=ent['blocks'],
                step_ms=None, verdict='consulted' if ok else 'error',
                promoted=bool(ok),
                error=None if ok else f'promoted entry not consulted '
                                      f'(resolved {got})'))
            if not ok:
                failures += 1

    n_promoted = len(promoted_entries)
    print(f'tune_kernels: {len(targets)} targets, {n_promoted} promoted, '
          f'{failures} failures; table at {tuning.cache_file()}',
          file=sys.stderr)
    return 1 if failures else 0


if __name__ == '__main__':
    sys.exit(main())
