"""CPU gate for kNN-free large-assembly serving (`make assembly-smoke`).

The ISSUE 18 acceptance harness for `attention_mode='global'`: a large
assembly must be SERVED — through a real `InferenceEngine` bucket, not a
bare `module.apply` — with O(n) activation memory, and every claim must
land in one schema'd `assembly` record that PERF_BUDGETS.json judges.

Five gates, exit non-zero on any failure:

  1. PARITY — the streaming global path vs the `global_materialize=True`
     control arm (every [b, n, n, ...] per-edge tensor in memory, plain
     autodiff) on IDENTICAL parameters, both contraction arms (dense CG
     and so2 banded), under a real node mask (padded rows), at an n
     large enough that the stream genuinely chunks. <= 1e-4 max-abs.
  2. EQUIVARIANCE — the streaming global model's equivariance L2 must
     stay under 1e-5 (tighter than the flash gate: the global path has
     no neighbor-selection discretization to hide behind).
  3. SHARDED — a fresh 2-virtual-device subprocess compiles the
     sequence-parallel ('ring') global arm and proves it ALL-GATHER-FREE
     via `analyze_hlo_comm` on the partitioned HLO (the PR 11 residue:
     the flash gather used to bypass the exchange scope), plus parity
     vs the unsharded stream.
  4. SERVED — n=4096 (the first large-assembly bucket) goes through an
     AOT `InferenceEngine` global bucket end to end: warmup compiles,
     one real padded request is answered, ZERO post-warmup compiles,
     and the oversize rejection carries the client-actionable
     `max_bucket`. The bucket's peak activation HBM comes off the PR 6
     cost ledger of the SERVING executable.
  5. MEMORY — the materialized control arm at the same n is
     compile-ONLY (AOT lower+compile; XLA's static peak estimate —
     nothing is executed, which is the point: on most hosts the
     materialized arm cannot run at 4096 at all). The ledger ratio
     materialized/global must clear the >=3x floor — enforced by
     scripts/perf_gate.py over the banked ASSEMBLY_SWEEP.jsonl.

`--inject-regression` writes a corrupted record (ratio 1.0, failed
equivariance, zero rows served, post-warmup compiles) and requires
`perf_gate.py` to FIRE on it, then exits 1 — proving the committed
budgets actually bite (the Makefile asserts rc==1).

    python scripts/assembly_smoke.py [--metrics ASSEMBLY.jsonl]
        [--bucket 4096] [--parity-n 96] [--sp-n 64]
"""
import argparse
import json
import os
import subprocess
import sys
import uuid

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

PARITY_TOL = 1e-4
EQ_TOL = 1e-5

MODULE_KW = dict(num_tokens=24, dim=8, depth=1, num_degrees=2,
                 output_degrees=2, reduce_dim_out=True, attend_self=True,
                 use_null_kv=True, heads=2, dim_head=8, pallas=False,
                 attention_mode='global')


def _build(backend='dense', **overrides):
    from se3_transformer_tpu.models.se3_transformer import (
        SE3TransformerModule,
    )
    return SE3TransformerModule(**{**MODULE_KW, 'conv_backend': backend,
                                   **overrides})


def _init_params(mod, feats, coors, mask):
    import jax
    return jax.jit(mod.init, static_argnames=('return_type',))(
        jax.random.PRNGKey(0), feats, coors, mask=mask,
        return_type=1)['params']


def _toy_batch(n, seed=0):
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.RandomState(seed)
    feats = jnp.asarray(rng.randint(0, 24, (1, n)))
    coors = jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                        jnp.float32)
    return feats, coors


def sp_child(n: int) -> int:
    """Runs in a fresh process under XLA_FLAGS virtual devices: compile
    the sp=2 ring global arm, analyze its partitioned HLO, check parity
    vs the unsharded stream. Prints ONE JSON line for the parent."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from se3_transformer_tpu.parallel.exchange import analyze_hlo_comm

    devices = jax.devices('cpu')
    assert len(devices) >= 2, \
        f'sp child needs 2 virtual devices, got {len(devices)}'
    mesh = Mesh(np.array(devices[:2]), ('sp',))

    feats, coors = _toy_batch(n)
    mask = jnp.ones((1, n), bool)
    plain = _build()
    params = _init_params(plain, feats, coors, mask)
    ref = plain.apply({'params': params}, feats, coors, mask=mask,
                      return_type=1)

    ring = _build(sequence_parallel='ring', mesh=mesh)

    def fn(f, c, m):
        return ring.apply({'params': params}, f, c, mask=m,
                          return_type=1)

    # the output stays sharded along the node axis — sequence-parallel
    # serving hands each host its own rows; re-replicating here would
    # itself be the full-width gather the gate exists to forbid
    compiled = jax.jit(
        fn, out_shardings=NamedSharding(mesh, P(None, 'sp')),
    ).lower(feats, coors, mask).compile()
    analysis = analyze_hlo_comm(compiled.as_text(), full_width_dim=n)
    out = np.asarray(jax.device_get(compiled(feats, coors, mask)))
    parity = float(np.abs(out - np.asarray(ref)).max())
    print(json.dumps(dict(
        sp=2, n=n, parity=parity,
        all_gather_free=analysis['all_gather_free'],
        full_width_all_gathers=analysis['full_width_all_gathers'],
        collectives={k: v.get('count') for k, v in
                     analysis['collectives'].items()})))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='kNN-free global-attention large-assembly serving '
                    'gate: parity + equivariance + sharded HLO proof + '
                    'engine-served bucket + ledger memory ratio')
    ap.add_argument('--metrics', default=None,
                    help='write the schema-valid assembly stream here')
    ap.add_argument('--bucket', type=int, default=4096,
                    help='the large-assembly engine bucket to serve')
    ap.add_argument('--parity-n', type=int, default=96,
                    help='node count for the parity/equivariance stage '
                         '(>=32 so the stream genuinely chunks)')
    ap.add_argument('--sp-n', type=int, default=64)
    ap.add_argument('--sp-child', type=int, default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument('--inject-regression', action='store_true',
                    help='write a corrupted record and require the perf '
                         'gate to fire on it (exits 1 when it does)')
    args = ap.parse_args(argv)

    if args.sp_child is not None:
        return sp_child(args.sp_child)

    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    run_id = f'assembly_smoke_{uuid.uuid4().hex[:8]}'

    if args.inject_regression:
        return inject_regression(args, run_id)

    ok = True
    n = args.parity_n
    feats, coors = _toy_batch(n)
    # padded batch: trailing rows are mask=False — parity must hold on
    # the real rows with the pad excluded from every pair reduction
    mask = jnp.asarray(np.arange(n) < n - 7)[None]

    # ---- 1/2: parity (both arms) + equivariance, identical params ---- #
    from se3_transformer_tpu.utils.validation import equivariance_l2
    eq = None
    for backend in ('dense', 'so2'):
        stream = _build(backend)
        ctrl = _build(backend, global_materialize=True)
        params = _init_params(stream, feats, coors, mask)
        out = stream.apply({'params': params}, feats, coors, mask=mask,
                           return_type=1)
        ref = ctrl.apply({'params': params}, feats, coors, mask=mask,
                         return_type=1)
        diff = float(jnp.abs(out - ref).max())
        print(f'{backend}-arm global stream vs materialized parity: '
              f'{diff:.3g}')
        if not diff < PARITY_TOL:
            print(f'FAIL: {backend}-arm parity {diff} >= {PARITY_TOL}')
            ok = False
        if backend == 'dense':
            parity = diff
            eq = equivariance_l2(stream, params, feats, coors, mask)
            print(f'global-mode equivariance L2: {eq:.3g}')
            if not eq < EQ_TOL:
                print(f'FAIL: equivariance {eq} >= {EQ_TOL}')
                ok = False

    # ---- 3: sp=2 ring composition, all-gather-free by HLO ---------- #
    env = dict(os.environ,
               XLA_FLAGS=(os.environ.get('XLA_FLAGS', '')
                          + ' --xla_force_host_platform_device_count=2'),
               JAX_PLATFORMS='cpu')
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         '--sp-child', str(args.sp_n)],
        capture_output=True, text=True, cwd=REPO, env=env)
    sys.stderr.write(proc.stderr)
    if proc.returncode != 0:
        print(f'FAIL: sp child exited {proc.returncode}')
        return 1
    sp = json.loads(proc.stdout.strip().splitlines()[-1])
    print(f'sp=2 ring global arm: parity {sp["parity"]:.3g}, '
          f'collectives {sp["collectives"]}, '
          f'all_gather_free={sp["all_gather_free"]}')
    if not sp['all_gather_free']:
        print(f'FAIL: sharded global arm re-materialized full-width '
              f'operands: {sp["full_width_all_gathers"]}')
        ok = False
    if not sp['parity'] < PARITY_TOL:
        print(f'FAIL: sharded parity {sp["parity"]} >= {PARITY_TOL}')
        ok = False

    # ---- 4: SERVE n through a real engine bucket ------------------- #
    from se3_transformer_tpu.inference.admission import RequestRejected
    from se3_transformer_tpu.inference.engine import InferenceEngine

    bucket = args.bucket
    stream = _build()
    params = _init_params(stream, feats, coors, mask)
    engine = InferenceEngine(
        stream, params, buckets=(bucket,), batch_size=1, return_type=1,
        # chain adjacency is a kNN-trunk concept; the global mode's
        # admission contract is purely bucket-shaped
        with_chain_adjacency=False)
    compiles_at_warmup = len(engine.compile_seconds)
    served_len = bucket - 57    # a real (non-bucket-exact) request
    tokens = np.random.RandomState(1).randint(0, 24, served_len)
    coords = np.cumsum(
        np.random.RandomState(1).normal(size=(served_len, 3)),
        axis=0).astype(np.float32)
    out = engine.predict(tokens, coords)
    assert out.shape[0] == served_len, out.shape
    if not np.isfinite(out).all():
        print('FAIL: served output is not finite')
        ok = False
    post_warmup_compiles = len(engine.compile_seconds) - compiles_at_warmup
    stats = engine.stats()
    bucket_served = stats['rows_served'].get(str(bucket), 0)
    key = (bucket, 1, 'float32')
    global_peak = int(engine.cost_payloads[key]['peak_bytes'])
    print(f'engine served n={served_len} through bucket {bucket}: '
          f'rows_served={bucket_served}, '
          f'post_warmup_compiles={post_warmup_compiles}, '
          f'peak_bytes={global_peak}')
    if bucket_served < 1:
        print('FAIL: no rows served through the large-assembly bucket')
        ok = False
    if post_warmup_compiles != 0:
        print(f'FAIL: {post_warmup_compiles} post-warmup compiles — the '
              f'serving cliff the AOT bucket exists to prevent')
        ok = False
    try:
        engine.predict(np.zeros(bucket + 511, np.int32),
                       np.zeros((bucket + 511, 3), np.float32))
        print('FAIL: oversize request was not rejected')
        ok = False
    except RequestRejected as e:
        if e.detail.get('max_bucket') != bucket:
            print(f'FAIL: oversize rejection lacks actionable '
                  f'max_bucket: {e.detail}')
            ok = False
        else:
            print(f'oversize rejection carries max_bucket='
                  f'{e.detail["max_bucket"]}')

    # ---- 5: materialized control arm, compile-ONLY ----------------- #
    from se3_transformer_tpu.observability.costs import cost_payload
    ctrl = _build(global_materialize=True)

    def ctrl_fn(p, t, c, m):
        return ctrl.apply({'params': p}, t, c, mask=m, return_type=1)

    def sds(a):
        return jax.ShapeDtypeStruct(np.shape(a),
                                    getattr(a, 'dtype', np.float32))

    abstract_params = jax.tree_util.tree_map(sds, params)
    compiled = jax.jit(ctrl_fn).lower(
        abstract_params,
        jax.ShapeDtypeStruct((1, bucket), jnp.int32),
        jax.ShapeDtypeStruct((1, bucket, 3), jnp.float32),
        jax.ShapeDtypeStruct((1, bucket), jnp.bool_)).compile()
    mat_cost = cost_payload(compiled,
                            label=f'assembly_materialized,n={bucket}')
    mat_peak = int(mat_cost['peak_bytes'])
    ratio = round(mat_peak / max(global_peak, 1), 3)
    print(f'peak activation HBM at n={bucket}: streaming {global_peak} '
          f'vs materialized {mat_peak} (ratio {ratio}x; the >=3x floor '
          f'is enforced by scripts/perf_gate.py)')

    if args.metrics:
        from se3_transformer_tpu.observability.report import (
            write_record_stream,
        )
        from se3_transformer_tpu.observability.schema import (
            validate_stream,
        )
        body = dict(
            kind='assembly',
            label=f'global_serving,n={bucket},dim={MODULE_KW["dim"]}',
            n=served_len, bucket=bucket,
            global_peak_bytes=global_peak,
            materialized_peak_bytes=mat_peak,
            hbm_materialized_vs_global=ratio,
            parity_linf=parity, equivariance_l2=eq,
            bucket_served=int(bucket_served),
            post_warmup_compiles=int(post_warmup_compiles),
            sp=2, sp_all_gather_free=bool(sp['all_gather_free']),
            sp_parity_linf=sp['parity'],
            max_bucket_rejection=True,
            cost=dict(serving=engine.cost_payloads[key],
                      materialized=mat_cost))
        write_record_stream(args.metrics, run_id, [body])
        info = validate_stream(args.metrics)
        print(f'schema ok: {info["records"]} records {info["kinds"]}')

    summary = dict(ok=ok, bucket=bucket, served=int(bucket_served),
                   post_warmup_compiles=int(post_warmup_compiles),
                   hbm_materialized_vs_global=ratio,
                   parity_linf=parity, equivariance_l2=eq,
                   sp_all_gather_free=bool(sp['all_gather_free']))
    print(json.dumps(summary))
    return 0 if ok else 1


def inject_regression(args, run_id):
    """Write a corrupted assembly record and require the committed
    budgets to fire on it. Exits 1 when the gate bites (the Makefile
    asserts exactly that), 2 when the corruption goes UNDETECTED."""
    assert args.metrics, '--inject-regression needs --metrics'
    from se3_transformer_tpu.observability.report import (
        write_record_stream,
    )
    body = dict(
        kind='assembly', label='global_serving,INJECTED',
        n=args.bucket - 57, bucket=args.bucket,
        # the three regressions the budgets exist to catch: the memory
        # win gone (ratio 1.0), equivariance broken, nothing actually
        # served (plus the serving-cliff compile, which obs_report's
        # --require assembly gate also rejects)
        global_peak_bytes=1 << 30, materialized_peak_bytes=1 << 30,
        hbm_materialized_vs_global=1.0,
        parity_linf=0.5, equivariance_l2=0.5,
        bucket_served=0, post_warmup_compiles=3)
    write_record_stream(args.metrics, run_id, [body])
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, 'perf_gate.py'),
         args.metrics],
        capture_output=True, text=True, cwd=REPO)
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    if proc.returncode == 0:
        print('INJECTED REGRESSION NOT CAUGHT: perf_gate passed a '
              'record with ratio 1.0, broken equivariance, and zero '
              'rows served — the budgets are not wired')
        return 2
    print('perf gate FIRED on the injected assembly regression '
          f'(rc={proc.returncode}) — budgets are live')
    return 1


if __name__ == '__main__':
    sys.exit(main())
