"""CPU gate for the SE3TransformerV2 eSCN-direct family (`make v2-smoke`).

Three gates, exit non-zero on any failure:

  1. EQUIVARIANCE — the v2 arm's equivariance L2 must stay under 1e-4
     at every swept degree (~1e-6 in practice: the per-m banded blocks
     commute exactly with the frame rotations, and the separable S2
     activation's per-degree grids are sized to quadrature accuracy);
  2. SANITY — wherever the v1+so2 baseline arm ran, its step time and
     the so2_vs_v2 ratio must be present and non-degenerate (the
     family A/B the committed degree-6 win budget judges);
  3. SCHEMA + RECORD — the per-degree A/B payload from
     bench.v2_degrees_main is written as a schema'd `v2_sweep` record
     (run_meta header, observability.schema validation). The Makefile
     target then runs `obs_report --require v2_sweep` and
     `perf_gate.py` on the stream, so the committed degree-6
     throughput floor judges the fresh numbers.

    python scripts/v2_smoke.py [--metrics V2.jsonl]
        [--degrees 2,4,6] [--so2-max 4] [--steps 5]

Default degrees are 2,4,6 with the v1+so2 baseline capped at degree 4
(the smoke's CPU budget — the so2 arm's degree-6 compile is the slow
part, and the degree-6 v2 throughput floor needs only the v2 arm). The
committed V2_SWEEP.jsonl evidence was produced with --degrees 2,4,6,8
--so2-max 6, which is what the degree-6 win and degree-8 equivariance
budgets judge.
"""
import argparse
import json
import os
import sys
import uuid

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

EQ_TOL = 1e-4


def main(argv=None):
    ap = argparse.ArgumentParser(
        description='v2 model-family equivariance + degree-sweep '
                    'record gate')
    ap.add_argument('--metrics', default=None,
                    help='write the schema-valid v2_sweep stream here')
    ap.add_argument('--degrees', default='2,4,6')
    ap.add_argument('--so2-max', type=int, default=4)
    ap.add_argument('--steps', type=int, default=5)
    args = ap.parse_args(argv)
    degrees = [int(x) for x in args.degrees.split(',')]

    import jax
    jax.config.update('jax_platforms', 'cpu')

    import bench

    record = bench.v2_degrees_main(degrees, so2_max=args.so2_max,
                                   steps=args.steps)

    ok = True
    for d, entry in sorted(record['degrees'].items(), key=lambda kv:
                           int(kv[0])):
        eq = entry.get('equivariance_l2_v2')
        if eq is None or eq >= EQ_TOL:
            print(f'FAIL: v2 equivariance L2 {eq} >= {EQ_TOL} at '
                  f'degree {d}')
            ok = False
        if 'so2_step_ms' in entry:
            if entry.get('so2_vs_v2', 0) <= 0:
                print(f'FAIL: degenerate so2_vs_v2 at degree {d}: '
                      f'{entry.get("so2_vs_v2")!r}')
                ok = False

    if args.metrics:
        from se3_transformer_tpu.observability.report import (
            write_record_stream,
        )
        from se3_transformer_tpu.observability.schema import (
            validate_stream,
        )
        body = dict(kind='v2_sweep', label=record['metric'],
                    degrees=record['degrees'],
                    value=record['value'], unit=record['unit'],
                    timing=record['timing'])
        write_record_stream(args.metrics,
                            f'v2_smoke_{uuid.uuid4().hex[:8]}', [body])
        info = validate_stream(args.metrics)
        print(f'schema ok: {info["records"]} records {info["kinds"]}')

    summary = dict(ok=ok, degrees=record['degrees'])
    print(json.dumps(summary))
    if not ok:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
