"""On-chip flagship knob/width probe: what fits one v5e, and at what cost.

Sweeps the flagship recipe over edge_chunks x dim (and optionally the
fast knobs), timing a few real optimizer steps per point and recording
fit/OOM + step_ms to a crash-safe JSONL (every point is appended as it
completes — a tunnel death loses at most the in-flight point).

Motivation (round 3): edge_chunks=8 was chosen while 9 GB of broadcast
index tensors still existed; after the MXU-gather fix the un-streamed
program may fit outright, and fewer chunks mean less lax.map overhead
(~0.9 s of the 4.05 s profiled forward was the chunk loop). The probe
also produces the max-width-per-chip table VERDICT r2 #2 asked for.

Usage: python scripts/tpu_probe.py [--out PROBE.jsonl] [--steps 3]
       [--fast] [--dims 64 96 128] [--chunks 0 2 8] [--batches 2 4]
"""
import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# a flagship-shape (n=1024) timing below ~300 ms is a dying-tunnel
# artifact (observed: a 31 ms "record" appended seconds before the
# 13:29Z tunnel death), not a measurement. Scaled by node count so
# legitimate small-shape probes (--nodes 256 runs in ~80 ms) still
# register as done. Shared with tpu_session._best_probe_batch.
def min_real_step_ms(n: int) -> float:
    return max(30.0, 300.0 * n / 1024.0)


def package_fingerprint(ignore_env: bool = False):
    """Tree hash of the package directory at HEAD — the identity under
    which probe measurements stay valid. Docs/scripts commits don't
    disturb it; any package code change retires prior records from the
    --skip-done set and the batch election (uncommitted package edits
    are invisible to it, so probe sessions must run from a committed
    tree — the session loop always does).

    SE3_TPU_CODE_REV overrides the git lookup: a commit landing while a
    long-lived session is mid-stage-order would otherwise stamp
    measurements of the already-loaded old code with the new tree hash
    (observed 15:42Z round 4: the bias-unfolding commit landed while the
    pre-change session ran). tpu_session pins it at chip acquisition —
    computed via ignore_env=True (a stale env from the launching shell
    must not win) — and eagerly imports the package in the same breath
    so the pinned rev IS the loaded code."""
    env = None if ignore_env else os.environ.get('SE3_TPU_CODE_REV')
    if env:
        return env
    try:
        return subprocess.run(
            ['git', 'rev-parse', 'HEAD:se3_transformer_tpu'],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            capture_output=True, text=True, timeout=30,
        ).stdout.strip() or None
    except Exception:  # noqa: BLE001 - fingerprint is best-effort
        return None


def probe_point(dim, chunks, fast, steps, n=1024, k=32, reversible=True,
                batch=1):
    """One sweep point, reusing run_baselines.run_config (the shared
    denoise train-step harness) so probe numbers stay comparable with
    the baseline table."""
    import numpy as np
    import run_baselines
    from se3_transformer_tpu.training import recipes

    name = 'flagship_fast' if fast else 'flagship'
    module = recipes.RECIPES[name](
        dim=dim, num_neighbors=k, output_degrees=2, reduce_dim_out=True,
        edge_chunks=(chunks if chunks > 0 else None), reversible=reversible)
    rec = run_baselines.run_config(f'{name}-probe', module, n, steps,
                                   np.random.RandomState(0), batch=batch)
    return dict(step_ms=rec['step_ms'], compile_s=rec['compile_s'],
                nodes_steps_per_sec=rec['nodes_steps_per_sec'])


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'PROBE_TPU.jsonl'))
    ap.add_argument('--steps', type=int, default=3)
    ap.add_argument('--fast', action='store_true')
    ap.add_argument('--dims', type=int, nargs='+', default=[64, 96, 128])
    ap.add_argument('--chunks', type=int, nargs='+', default=[0, 2, 8])
    ap.add_argument('--nodes', type=int, default=1024)
    ap.add_argument('--batches', type=int, nargs='+', default=[2, 4])
    ap.add_argument('--nonrev', action='store_true',
                    help='also measure the unchunked non-reversible arm. '
                         'OFF by default: its fresh multi-minute compile '
                         'killed the tunnel twice in round 4 (12:51Z and '
                         '13:29Z), and each death restarts the whole '
                         'session loop before the batch sweep is reached')
    ap.add_argument('--skip-done', action='store_true',
                    help='skip points that already have a fits=true record '
                         'with a sane timing in --out (the session loop '
                         're-runs the probe after every tunnel death; '
                         'without this, earlier points are re-measured '
                         'each cycle and the sweep never advances)')
    args = ap.parse_args(argv)

    import jax
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()
    backend = jax.default_backend()
    print(f'backend: {backend}', flush=True)

    fingerprint = package_fingerprint()
    done = {}  # point key -> fits (bool): skipped points replay their result
    if args.skip_done and fingerprint:
        try:
            with open(args.out) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    # done = measured under the SAME package code, same
                    # shape, on a real chip: either a sane timing
                    # (MIN_REAL_STEP_MS guards the artifact records) or
                    # a deterministic OOM (fits=false with an error —
                    # no point re-paying its multi-minute compile every
                    # relaunch cycle)
                    if rec.get('code_rev') != fingerprint:
                        continue
                    if rec.get('backend') in (None, 'cpu'):
                        continue
                    real = rec.get('step_ms', 0) > min_real_step_ms(
                        rec.get('n') or 1024)
                    # only a DETERMINISTIC memory failure replays as
                    # "does not fit"; any other error (a transient
                    # infra failure whose message misses tunnel_sigs)
                    # must be re-attempted next cycle
                    from se3_transformer_tpu.utils.helpers import (
                        is_oom_error,
                    )
                    err = rec.get('error') or ''
                    oom = (not rec.get('fits')) and (
                        rec.get('oom') or is_oom_error(err)
                        or 'oom' in err.lower())
                    if real or oom:
                        done[(rec.get('dim'), rec.get('edge_chunks'),
                              rec.get('reversible', True),
                              rec.get('batch', 1), rec.get('fast'),
                              rec.get('n'))] = bool(rec.get('fits'))
        except OSError:
            pass

    # tunnel-death failures must PROPAGATE so tpu_session's
    # retryable-exit detection fires — recording them as fits=False
    # would both corrupt the table and end the session loop. OOMs are
    # carved out inside is_tunnel_error (helpers: one shared list).
    from se3_transformer_tpu.utils.helpers import is_tunnel_error

    def run_and_record(**pt):
        key = (pt['dim'], pt['edge_chunks'], pt.get('reversible', True),
               pt.get('batch', 1), args.fast, args.nodes)
        if key in done:
            print(f'skip (already measured, fits={done[key]}): {pt}',
                  flush=True)
            return dict(pt, fits=done[key], skipped=True)
        rec = dict(pt)
        rec['backend'] = backend
        rec['n'] = args.nodes
        rec['code_rev'] = fingerprint
        try:
            rec.update(probe_point(pt['dim'], pt['edge_chunks'], args.fast,
                                   args.steps, n=args.nodes,
                                   reversible=pt.get('reversible', True),
                                   batch=pt.get('batch', 1)))
            rec['fits'] = True
        except Exception as e:  # noqa: BLE001
            from se3_transformer_tpu.utils.helpers import is_oom_error
            msg = f'{type(e).__name__}: {e}'
            if is_tunnel_error(msg):
                raise  # retryable infrastructure failure, not a fit result
            rec['fits'] = False
            rec['error'] = msg[:220]
            # classify at FULL-message time: the 220-char truncation can
            # cut the OOM text off (observed: the HTTP-500 wrapper alone
            # survived), and the resume matcher must not re-pay this
            # arm's compile every relaunch
            rec['oom'] = is_oom_error(msg)
        print(json.dumps(rec), flush=True)
        with open(args.out, 'a') as f:
            f.write(json.dumps(rec) + '\n')
        if rec.get('oom'):
            # a RUNTIME OOM can leave the device allocator poisoned —
            # every later allocation in this process then fails
            # instantly (observed 22:12Z: the whole remaining stage
            # order burned down in 9 s). Canary-probe the allocator;
            # if poisoned, relaunch from a fresh process. The arm is
            # already durably recorded (rec['oom']), so the relaunch
            # skips it — no retry cycle.
            try:
                import jax.numpy as jnp
                (jnp.zeros((8,), jnp.float32) + 1).block_until_ready()
            except Exception as ce:  # noqa: BLE001
                raise RuntimeError(
                    'RELAUNCH_NEEDED: device allocator poisoned after '
                    f'recorded OOM ({type(ce).__name__})') from ce
        return rec

    # cheapest-first so early tunnel deaths still leave a table; dims
    # outer (a width that OOMs at chunks=8 is skipped at lower chunks)
    for dim in args.dims:
        dim_fits = False
        for chunks in sorted(args.chunks, reverse=True):  # more chunks first
            rec = run_and_record(dim=dim, edge_chunks=chunks, fast=args.fast)
            dim_fits = dim_fits or rec['fits']
            if not rec['fits']:
                # fewer chunks only use MORE memory: once this dim fails
                # at the most-chunked setting, lower settings are doomed
                # — don't spend a multi-minute compile each to prove it
                print(f'dim={dim}: skipping lower chunk settings after '
                      f'failure at edge_chunks={chunks}', flush=True)
                break
            if chunks == 0 and args.nonrev:
                # unchunked fit: also measure without the reversible
                # remat (the recompute costs ~one extra forward per
                # step) — the highest-memory, fastest-possible point.
                # Opt-in (--nonrev): see the flag's help for the
                # tunnel-death history
                run_and_record(dim=dim, edge_chunks=0, reversible=False,
                               fast=args.fast)
        if dim_fits and dim == args.dims[0]:
            # per-chip throughput scales with batch while HBM lasts (the
            # reference's own training runs 16 accumulated micro-batches,
            # denoise.py:13,55) — measure the batch ceiling at the
            # primary width. Primary chunk setting matches what the
            # batched BENCH record will run (the fast recipe is
            # unchunked since the round-4 re-cut); a batch that OOMs
            # unchunked falls back to the most memory-lean chunked
            # setting and the sweep continues there, so the election
            # (tpu_session._best_probe_batch) can pick a (batch,
            # edge_chunks) pair the bench is guaranteed to fit.
            bchunks = 0 if args.fast else max(args.chunks)
            for b in sorted(args.batches):
                rec = run_and_record(dim=dim, edge_chunks=bchunks,
                                     batch=b, fast=args.fast)
                if not rec['fits'] and bchunks != max(args.chunks):
                    bchunks = max(args.chunks)
                    rec = run_and_record(dim=dim, edge_chunks=bchunks,
                                         batch=b, fast=args.fast)
                if not rec['fits']:
                    break
        if not dim_fits:
            print(f'dim={dim} fits at no chunk setting; stopping sweep',
                  flush=True)
            break


if __name__ == '__main__':
    main()
