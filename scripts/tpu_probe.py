"""On-chip flagship knob/width probe: what fits one v5e, and at what cost.

Sweeps the flagship recipe over edge_chunks x dim (and optionally the
fast knobs), timing a few real optimizer steps per point and recording
fit/OOM + step_ms to a crash-safe JSONL (every point is appended as it
completes — a tunnel death loses at most the in-flight point).

Motivation (round 3): edge_chunks=8 was chosen while 9 GB of broadcast
index tensors still existed; after the MXU-gather fix the un-streamed
program may fit outright, and fewer chunks mean less lax.map overhead
(~0.9 s of the 4.05 s profiled forward was the chunk loop). The probe
also produces the max-width-per-chip table VERDICT r2 #2 asked for.

Usage: python scripts/tpu_probe.py [--out PROBE.jsonl] [--steps 3]
       [--fast] [--dims 64 96 128] [--chunks 0 2 8] [--batches 2 4]
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def probe_point(dim, chunks, fast, steps, n=1024, k=32, reversible=True,
                batch=1):
    """One sweep point, reusing run_baselines.run_config (the shared
    denoise train-step harness) so probe numbers stay comparable with
    the baseline table."""
    import numpy as np
    import run_baselines
    from se3_transformer_tpu.training import recipes

    name = 'flagship_fast' if fast else 'flagship'
    module = recipes.RECIPES[name](
        dim=dim, num_neighbors=k, output_degrees=2, reduce_dim_out=True,
        edge_chunks=(chunks if chunks > 0 else None), reversible=reversible)
    rec = run_baselines.run_config(f'{name}-probe', module, n, steps,
                                   np.random.RandomState(0), batch=batch)
    return dict(step_ms=rec['step_ms'], compile_s=rec['compile_s'],
                nodes_steps_per_sec=rec['nodes_steps_per_sec'])


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'PROBE_TPU.jsonl'))
    ap.add_argument('--steps', type=int, default=3)
    ap.add_argument('--fast', action='store_true')
    ap.add_argument('--dims', type=int, nargs='+', default=[64, 96, 128])
    ap.add_argument('--chunks', type=int, nargs='+', default=[0, 2, 8])
    ap.add_argument('--nodes', type=int, default=1024)
    ap.add_argument('--batches', type=int, nargs='+', default=[2, 4])
    args = ap.parse_args(argv)

    import jax
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()
    backend = jax.default_backend()
    print(f'backend: {backend}', flush=True)

    # tunnel-death signatures: such failures must PROPAGATE so
    # tpu_session's retryable-exit detection fires — recording them as
    # fits=False would both corrupt the table and end the session loop
    tunnel_sigs = ('unavailable', 'broken pipe', 'network error',
                   'connection refused', 'remote_compile')

    def run_and_record(**pt):
        rec = dict(pt)
        rec['backend'] = backend
        try:
            rec.update(probe_point(pt['dim'], pt['edge_chunks'], args.fast,
                                   args.steps, n=args.nodes,
                                   reversible=pt.get('reversible', True),
                                   batch=pt.get('batch', 1)))
            rec['fits'] = True
        except Exception as e:  # noqa: BLE001
            msg = f'{type(e).__name__}: {e}'
            if any(s in msg.lower() for s in tunnel_sigs):
                raise  # retryable infrastructure failure, not a fit result
            rec['fits'] = False
            rec['error'] = msg[:220]
        print(json.dumps(rec), flush=True)
        with open(args.out, 'a') as f:
            f.write(json.dumps(rec) + '\n')
        return rec

    # cheapest-first so early tunnel deaths still leave a table; dims
    # outer (a width that OOMs at chunks=8 is skipped at lower chunks)
    for dim in args.dims:
        dim_fits = False
        for chunks in sorted(args.chunks, reverse=True):  # more chunks first
            rec = run_and_record(dim=dim, edge_chunks=chunks, fast=args.fast)
            dim_fits = dim_fits or rec['fits']
            if not rec['fits']:
                # fewer chunks only use MORE memory: once this dim fails
                # at the most-chunked setting, lower settings are doomed
                # — don't spend a multi-minute compile each to prove it
                print(f'dim={dim}: skipping lower chunk settings after '
                      f'failure at edge_chunks={chunks}', flush=True)
                break
            if chunks == 0:
                # unchunked fit: also measure without the reversible
                # remat (the recompute costs ~one extra forward per
                # step) — the highest-memory, fastest-possible point
                run_and_record(dim=dim, edge_chunks=0, reversible=False,
                               fast=args.fast)
        if dim_fits and dim == args.dims[0]:
            # per-chip throughput scales with batch while HBM lasts (the
            # reference's own training runs 16 accumulated micro-batches,
            # denoise.py:13,55) — measure the batch ceiling at the
            # primary width using the most memory-lean chunk setting
            for b in sorted(args.batches):
                rec = run_and_record(dim=dim, edge_chunks=max(args.chunks),
                                     batch=b, fast=args.fast)
                if not rec['fits']:
                    break
        if not dim_fits:
            print(f'dim={dim} fits at no chunk setting; stopping sweep',
                  flush=True)
            break


if __name__ == '__main__':
    main()
