"""Automated perf-regression gate: committed budgets vs fresh records.

Five PRs of JSONL record streams (bench / comm / cost / serve / width
rows) were evidence; this gate turns them into ENFORCED budgets. A
committed budget file (PERF_BUDGETS.json, seeded from the round-5
session records) declares per-metric floors/ceilings with noise
margins — including per-mesh-axis collective-byte budgets, the
enforcement mechanism ROADMAP item 5 asks for — and this script
compares record streams against them, exiting non-zero with a
readable diff on any breach.

    python scripts/perf_gate.py [RECORDS.jsonl ...]
        [--budgets PERF_BUDGETS.json] [--fresh-cost STREAM.jsonl]
        [--inject-regression [NAME]] [--strict]

With no record paths, the committed evidence set is gated
(BENCH_r05.json + WIDTH_TABLE.jsonl) — `make perf-gate` additionally
produces a FRESH toy cost record (--fresh-cost compiles the toy
denoise train step on CPU and ledgers it through observability.costs),
then re-runs with --inject-regression and asserts the non-zero exit:
the gate must both pass on healthy numbers AND actually fire.

Budget semantics (see PERF_BUDGETS.json):
  * `kind`   — which records the budget applies to: 'bench' (records
    with metric/value/unit), 'width' (width_table rows), or a
    telemetry `kind` (comm / cost / serve / profile ...).
  * `match`  — field -> expected filters (dotted paths; a string value
    matches as substring, anything else as equality).
  * `field`  — dotted path of the gated value.
  * one of `min` / `max` / `equals`, with `margin` (relative): a min
    budget passes at value >= min*(1-margin), a max budget at
    value <= max*(1+margin). `missing` says what an absent field
    means: 'fail' (default), 'zero' (absent collective class = 0
    bytes), or 'skip'.
  * evaluation uses the LAST matching record — streams are
    append-only chronological, so the latest evidence is gated and
    historical rows can never permanently trip a tightened budget.
    `group_by` (dotted path, e.g. "sp", or a comma-separated list of
    paths, e.g. "dp,sp,tp") instead judges the latest record of EVERY
    distinct value (tuple of values) of those fields, so a proof bit
    over a sweep ("all_gather_free at every sp" / "at every mesh
    point") cannot be masked by the final sweep point being clean.
  * `axis`   — annotation naming the mesh axis a collective budget
    guards (surfaced in the diff, so an sp-axis regression reads as
    one).

Budgets whose kind has no matching record are SKIPPED (reported;
--strict turns them into failures) — the committed set mixes
chip-session metrics with CPU-reproducible ones, and a CPU run must
not fail for lacking a TPU.
"""
import argparse
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

DEFAULT_BUDGETS = os.path.join(REPO, 'PERF_BUDGETS.json')
# SERVE_MULTI.jsonl: the banked `make serve-multi-smoke` stream, so the
# serving budgets (zero post-warmup compiles, router latency ceiling,
# continuous-admission proof bit) are judged by a plain `make perf-gate`.
# SO2_SWEEP.jsonl: the banked `make so2-smoke` degree-sweep stream, so
# the so2-vs-dense degree-4 win + throughput floor are judged too.
# FLASH_AB.jsonl: the banked `make flash-smoke` streaming-attention A/B
# stream, so the fused arm's step-time + peak-HBM wins and its
# equivariance gate are judged by a plain `make perf-gate`.
# CHAOS_SMOKE.jsonl: the banked `make chaos-smoke` fault-domain stream,
# so the zero-lost-requests contract, the observed quarantine->recovery
# transition, and the nonzero-injections proof bit are judged too.
# QUANT_AB.jsonl: the banked `make quant-smoke` fp32-vs-int8-mix serving
# A/B, so the argument-bytes ceiling, the implementation-parity gate,
# and the quantized equivariance gate are judged too.
# TRAIN_CHAOS.jsonl: the banked `make train-chaos-smoke` self-healing
# training stream, so the zero-divergence contract, the observed
# rollback, and the nonzero-injections proof bit are judged too.
# FLEET_CHAOS.jsonl: the banked `make serve-fleet-smoke` cross-host
# stream, so the fleet-wide zero-lost contract, the observed host
# quarantine->recovery, and the canary auto-rollback are judged too.
# SLO_SMOKE.jsonl: the banked `make slo-smoke` traced-fleet stream, so
# the fleet availability floor and the trace-completeness invariant
# (every resolved request = one complete single-root span tree) are
# judged by a plain `make perf-gate`.
# ASSEMBLY_SWEEP.jsonl: the banked `make assembly-smoke` kNN-free
# large-assembly stream, so the >=3x streaming-vs-materialized peak-HBM
# floor at the 4096 bucket, the tightened global equivariance ceiling,
# and the served-through-an-engine-bucket proof bit are judged too.
# MESH_SWEEP.jsonl: the banked `make mesh-smoke` composed-parallelism
# sweep (one row per (dp,sp,tp) mesh point on the 8-device sim), so the
# every-point all-gather-free proof bit, the per-axis ppermute /
# all-reduce byte ceilings, and the per-shard memory ceiling are judged
# by a plain `make perf-gate`.
# TRANSPORT_AB.jsonl: the banked `make transport-smoke` loadgen A/B
# (legacy connect-per-call JSON vs pooled multiplexed binary framing on
# the same seeded workload), so the binary-vs-legacy QPS floor, the p99
# ceiling, and the wire-bytes ceiling are judged by a plain
# `make perf-gate`.
DEFAULT_RECORDS = ('BENCH_r05.json', 'WIDTH_TABLE.jsonl',
                   'SERVE_MULTI.jsonl', 'SO2_SWEEP.jsonl',
                   'FLASH_AB.jsonl', 'CHAOS_SMOKE.jsonl',
                   'QUANT_AB.jsonl', 'TRAIN_CHAOS.jsonl',
                   'FLEET_CHAOS.jsonl', 'SLO_SMOKE.jsonl',
                   'V2_SWEEP.jsonl', 'ASSEMBLY_SWEEP.jsonl',
                   'MESH_SWEEP.jsonl', 'TRANSPORT_AB.jsonl')


# --------------------------------------------------------------------- #
# record loading / classification
# --------------------------------------------------------------------- #
def load_records(path):
    """JSONL stream, JSON list, or a single JSON object. BENCH_r0N.json
    wrappers ({"cmd", "rc", "parsed": {...bench record...}}) contribute
    their parsed record."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except ValueError:
        data = None
    if isinstance(data, list):
        return [r for r in data if isinstance(r, dict)]
    if isinstance(data, dict):
        if isinstance(data.get('parsed'), dict):
            return [data['parsed']]
        return [data]
    from se3_transformer_tpu.observability.report import load_jsonl
    return load_jsonl(path)


def record_kind(rec):
    if 'kind' in rec:
        return rec['kind']
    if 'metric' in rec and 'value' in rec and 'unit' in rec:
        return 'bench'
    if rec.get('weak_scaling') or 'per_shard_total_gb' in rec:
        return 'width'
    return None


# --------------------------------------------------------------------- #
# budget evaluation
# --------------------------------------------------------------------- #
def get_path(rec, dotted):
    cur = rec
    for part in dotted.split('.'):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def matches(rec, match):
    for path, want in (match or {}).items():
        got = get_path(rec, path)
        if isinstance(want, str) and not isinstance(want, bool):
            if got is None or want not in str(got):
                return False
        elif got != want:
            return False
    return True


def evaluate(budget, records):
    """-> (status, detail) with status in {'ok', 'FAIL', 'skip'}.

    With `group_by` (a dotted path, e.g. "sp", or several separated by
    commas, e.g. "dp,sp,tp"), the pool is partitioned by those fields'
    value tuple and the LAST record of EVERY group is judged — a
    proof-bit budget over a multi-point sweep (all_gather_free "at
    every sp" / "at every (dp,sp,tp) mesh point") can then never be
    masked by the final sweep point being clean while an earlier one
    regressed. Multi-key grouping matters on composed sweeps: grouped
    by "sp" alone, a clean (2,2,2) row would shadow a regressed
    (4,2,1) row that shares its sp value."""
    group_by = budget.get('group_by')
    if group_by:
        pool = [r for r in records if record_kind(r) == budget.get('kind')
                and matches(r, budget.get('match'))]
        if not pool:
            return 'skip', f'no matching {budget.get("kind")} record'
        keys = [k.strip() for k in group_by.split(',') if k.strip()]
        groups = {}
        for r in pool:   # later records overwrite: latest-per-group
            groups[tuple(str(get_path(r, k)) for k in keys)] = r
        results = [(key, *_evaluate_one(budget, [rec]))
                   for key, rec in sorted(groups.items())]
        fails = [f'{key[0] if len(key) == 1 else key}: {d}'
                 for key, s, d in results if s == 'FAIL']
        if fails:
            return 'FAIL', f'{len(fails)}/{len(results)} {group_by}-' \
                           f'groups breach: ' + '; '.join(fails)
        return 'ok', f'all {len(results)} {group_by}-groups ok ' \
                     f'(latest per group judged; e.g. {results[0][2]})'
    return _evaluate_one(budget, records)


def _evaluate_one(budget, records):
    name = budget.get('name', '?')
    kind = budget.get('kind')
    field = budget['field']
    margin = float(budget.get('margin', 0.0))
    pool = [r for r in records if record_kind(r) == kind
            and matches(r, budget.get('match'))]
    if not pool:
        return 'skip', f'no matching {kind} record'
    rec = pool[-1]   # latest evidence wins (streams are chronological)
    value = get_path(rec, field)
    if value is None:
        missing = budget.get('missing', 'fail')
        if missing == 'zero':
            value = 0
        elif missing == 'skip':
            return 'skip', f'field {field} absent in the matching record'
        else:
            return 'FAIL', f'field {field} MISSING in the matching ' \
                           f'record (of {len(pool)})'
    axis = f" [axis={budget['axis']}]" if budget.get('axis') else ''
    src = f'{len(pool)} matching, gated the last'
    if 'equals' in budget:
        want = budget['equals']
        if value != want:
            return 'FAIL', f'{field}={value!r} != required {want!r}' \
                           f'{axis} ({src})'
        return 'ok', f'{field}={value!r}{axis}'
    if 'min' in budget:
        floor = budget['min'] * (1.0 - margin)
        if not isinstance(value, (int, float)) or value < floor:
            return 'FAIL', (f'{field}={value} < floor {floor:.6g} '
                            f'(budget min {budget["min"]}, margin '
                            f'{margin:.0%}){axis} ({src})')
        return 'ok', f'{field}={value} >= {floor:.6g}{axis}'
    if 'max' in budget:
        ceil = budget['max'] * (1.0 + margin)
        if not isinstance(value, (int, float)) or value > ceil:
            return 'FAIL', (f'{field}={value} > ceiling {ceil:.6g} '
                            f'(budget max {budget["max"]}, margin '
                            f'{margin:.0%}){axis} ({src})')
        return 'ok', f'{field}={value} <= {ceil:.6g}{axis}'
    return 'FAIL', f'budget {name} declares no min/max/equals'


def synthesize_breach(budget):
    """A record matching the budget's filters but breaching its
    constraint by 2x the margin — the injected-regression arm that
    proves the gate actually fires."""
    rec = {}
    kind = budget.get('kind')
    if kind == 'bench':
        rec.update(metric='synthetic', value=0.0, unit='synthetic')
    elif kind == 'width':
        rec['weak_scaling'] = True
    else:
        rec['kind'] = kind
    for path, want in (budget.get('match') or {}).items():
        cur = rec
        parts = path.split('.')
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = want
    margin = float(budget.get('margin', 0.0))
    if 'equals' in budget:
        want = budget['equals']
        breach = (not want) if isinstance(want, bool) else f'not_{want}'
    elif 'min' in budget:
        breach = budget['min'] * (1.0 - margin) * 0.5
    else:
        breach = budget['max'] * (1.0 + margin) * 2.0
    cur = rec
    parts = budget['field'].split('.')
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = breach
    return rec


# --------------------------------------------------------------------- #
# fresh evidence: one toy cost record, compiled now on this host
# --------------------------------------------------------------------- #
def fresh_cost_stream(path):
    """Compile the toy denoise train step on CPU, ledger it through
    observability.costs, and write a schema-valid stream (run_meta +
    one `cost` record) to `path`. This is the gate's end-to-end proof
    that the ledger itself still works on the current tree."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    from se3_transformer_tpu.observability.report import write_record_stream
    from se3_transformer_tpu.training.denoise import (
        DenoiseConfig, DenoiseTrainer, synthetic_protein_batch,
    )
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()
    cfg = DenoiseConfig(num_nodes=48, accum_steps=1, num_degrees=2)
    trainer = DenoiseTrainer(cfg)
    batch = synthetic_protein_batch(cfg, trainer.np_rng)
    trainer.init(batch)
    body = trainer.cost_record(batch)
    body['label'] = 'perf_gate_toy,' + body.get('label', '')
    records = write_record_stream(
        path, f'perf_gate_{os.getpid()}', [body])
    flops = (f'{body["flops"]:.3g}' if body['flops'] is not None
             else 'None')
    print(f'fresh cost record -> {path} '
          f'(peak {body["peak_bytes"] / 2**20:.1f} MiB, '
          f'flops {flops}, source {body["source"]})',
          file=sys.stderr)
    return records


# --------------------------------------------------------------------- #
def main(argv=None):
    ap = argparse.ArgumentParser(
        description='compare record streams against committed perf '
                    'budgets; exit non-zero on regression')
    ap.add_argument('paths', nargs='*',
                    help=f'record files (default: the committed '
                         f'evidence set {DEFAULT_RECORDS})')
    ap.add_argument('--budgets', default=DEFAULT_BUDGETS)
    ap.add_argument('--fresh-cost', default=None, metavar='STREAM',
                    help='also compile the toy train step NOW, write '
                         'its cost record stream here, and gate it')
    ap.add_argument('--inject-regression', nargs='?', const='*',
                    default=None, metavar='NAME',
                    help='append a synthetic record breaching the '
                         'named budget (default: every budget) — the '
                         'gate must exit non-zero, proving it fires')
    ap.add_argument('--strict', action='store_true',
                    help='budgets with no matching record fail instead '
                         'of skipping')
    args = ap.parse_args(argv)

    with open(args.budgets) as f:
        spec = json.load(f)
    budgets = spec.get('budgets', [])
    default_margin = float(spec.get('default_margin', 0.0))
    for b in budgets:
        b.setdefault('margin', default_margin)

    paths = list(args.paths) or [
        p for p in (os.path.join(REPO, name) for name in DEFAULT_RECORDS)
        if os.path.exists(p)]
    records = []
    for p in paths:
        recs = load_records(p)
        print(f'{p}: {len(recs)} records', file=sys.stderr)
        records += recs
    if args.fresh_cost:
        records += fresh_cost_stream(args.fresh_cost)

    if args.inject_regression:
        injected = [b for b in budgets
                    if args.inject_regression in ('*', b.get('name'))]
        if not injected:
            print(f'no budget named {args.inject_regression!r}',
                  file=sys.stderr)
            return 2
        for b in injected:
            records.append(synthesize_breach(b))
        print(f'injected {len(injected)} synthetic breach record(s)',
              file=sys.stderr)

    failures = skips = 0
    for b in budgets:
        status, detail = evaluate(b, records)
        tag = {'ok': ' ok ', 'FAIL': 'FAIL', 'skip': 'SKIP'}[status]
        print(f'[{tag}] {b.get("name", "?")}: {detail}')
        if status == 'FAIL':
            failures += 1
        elif status == 'skip':
            skips += 1
    verdict = 'REGRESSION' if failures else 'ok'
    print(f'perf gate {verdict}: {len(budgets) - failures - skips} ok, '
          f'{failures} failed, {skips} skipped '
          f'(budgets {os.path.relpath(args.budgets, REPO)} v'
          f'{spec.get("version", "?")})')
    if failures:
        return 1
    if args.strict and skips:
        print('--strict: skipped budgets count as failures',
              file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
