"""Shared flagship train-step builder for the diagnostic scripts.

bench.py is the source of truth for the officially-timed program; this
module mirrors its setup (seeds, denoise objective, adam(1e-4), donated
make_sharded_train_step) so bench_diag.py and profile_flagship.py
measure the same program without three hand-copied replicas drifting
apart. Any change to bench.py's program must land here too — the
bench_diag loss-sequence cross-check (same seeds => identical losses)
catches a silent divergence.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_flagship_step(fast=True, remat=None, chunks=None, nodes=1024,
                        dim=64, batch=1):
    """Returns (step, params, opt_state, data, key, module): the
    bench-identical donated train step and its initial state.

    remat: remat_policy override ('none' forces the policy off);
    chunks: edge_chunks override (0 = unchunked)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from se3_transformer_tpu.parallel.sharding import make_sharded_train_step
    from se3_transformer_tpu.training import recipes
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    enable_compilation_cache()

    name = 'flagship_fast' if fast else 'flagship'
    overrides = dict(output_degrees=2, reduce_dim_out=True)
    if remat:
        overrides['remat_policy'] = None if remat == 'none' else remat
    if chunks is not None:
        overrides['edge_chunks'] = chunks or None
    module = recipes.RECIPES[name](dim=dim, **overrides)

    rng = np.random.RandomState(0)
    seqs = jnp.asarray(rng.normal(size=(batch, nodes, dim)), jnp.float32)
    coords = jnp.asarray(np.cumsum(
        rng.normal(size=(batch, nodes, 3)), axis=1), jnp.float32)
    coords = coords - coords.mean(axis=1, keepdims=True)
    masks = jnp.ones((batch, nodes), bool)

    def loss_fn(params, data, key):
        noise = jax.random.normal(key, data['coords'].shape,
                                  data['coords'].dtype)
        noised = data['coords'] + noise
        out = module.apply({'params': params}, data['seqs'], noised,
                           mask=data['masks'], return_type=1)
        loss = (((noised + out) - data['coords']) ** 2).sum(-1).mean()
        return loss, dict()

    init_fn = jax.jit(module.init, static_argnames=('return_type',))
    params = init_fn(jax.random.PRNGKey(0), seqs, coords, mask=masks,
                     return_type=1)['params']
    optimizer = optax.adam(1e-4)
    opt_state = optimizer.init(params)
    step = make_sharded_train_step(loss_fn, optimizer)
    data = dict(seqs=seqs, coords=coords, masks=masks)
    return step, params, opt_state, data, jax.random.PRNGKey(1), module


def validate_bench_record(rec: dict) -> dict:
    """Schema gate for banked flagship records (VERDICT r4 next #5): an
    on-chip record without a non-null equivariance_l2 must NOT be banked
    — two round-4 rows (the b=2/edge_chunks variants) regressed to null
    and the judge flagged it two rounds running. Raises ValueError; the
    session's crash-isolated stage runner logs the record (it is printed
    before the save) so the timing survives in the log for forensics
    without entering the record stream."""
    metric = str(rec.get('metric', ''))
    on_chip = 'backend=cpu' not in metric
    if on_chip and rec.get('equivariance_l2') is None:
        raise ValueError(
            f'refusing to bank an on-chip record without equivariance_l2 '
            f'(schema gate): {metric}')
    return rec
