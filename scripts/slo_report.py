"""Render a fleet JSONL stream into the one-dashboard SLO answer.

Usage:
    python scripts/slo_report.py STREAM.jsonl [--out DASHBOARD.json]

Reads the stream `make slo-smoke` (or any traced fleet run) banks and
renders the dashboard-shaped answer for "how is the fleet doing for
millions of users": fleet availability vs the SLO target, error-budget
burn rate, per-bucket merged-fleet latency percentiles (exact at bucket
resolution by construction — the per-host histograms share fixed
boundaries and merge by count addition), breaker-state dwell times,
rollout/rollback history, and the tracing completeness verdict
(complete span trees / orphans / cross-host redispatch hops).

Exits non-zero when the stream is NOT dashboard-grade:

  * schema violation anywhere in the stream;
  * no `slo` record (nothing to aggregate);
  * a `trace` record with orphan spans or completeness < 1.0 (the
    span-tree invariant is broken — latency attributions in the
    dashboard could not be trusted).

A stream with an `slo` record but no `trace` record renders with a
warning (SLO scraping works without tracing), so the tool stays usable
on partially-instrumented fleets. Never initializes a device backend —
works while the TPU tunnel is wedged.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from se3_transformer_tpu.observability.report import load_jsonl  # noqa: E402
from se3_transformer_tpu.observability.schema import (  # noqa: E402
    SchemaError, validate_record,
)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        description='fleet SLO + tracing dashboard from a JSONL stream')
    ap.add_argument('stream', help='JSONL stream with slo/trace records')
    ap.add_argument('--out', default=None,
                    help='also write the dashboard JSON here')
    return ap.parse_args(argv)


def _pct(x, digits=4):
    return f'{100.0 * x:.{digits}g}%'


def _render_slo(slo):
    eb = slo.get('error_budget', {})
    lines = [
        'fleet SLO',
        f'  hosts reporting     {slo.get("hosts")}',
        f'  availability        {_pct(slo["availability"])} '
        f'(target {_pct(eb.get("target", 0))})',
        f'  answered / failed   {slo.get("answered")} / '
        f'{slo.get("request_failures")} '
        f'(+{slo.get("timeouts", 0)} timeouts)',
        f'  error-budget burn   {eb.get("burn_rate")}x '
        f'(budget {_pct(eb.get("budget", 0))})',
    ]
    lines.append('  latency (merged-fleet percentiles, ms)')
    lines.append('    bucket   count      p50      p95      p99')
    for b, pct in sorted(slo.get('buckets', {}).items(),
                         key=lambda kv: int(kv[0])):
        lines.append(
            f'    {b:>6}  {pct.get("count", 0):>6}'
            + ''.join(f'  {pct.get(k) if pct.get(k) is not None else "-":>7}'
                      for k in ('p50_ms', 'p95_ms', 'p99_ms')))
    dwell = slo.get('breaker_dwell', {})
    if dwell:
        lines.append('  breaker dwell (s, share of window per state)')
        for host, states in sorted(dwell.items()):
            parts = ' '.join(f'{st}={round(sec, 3)}'
                             for st, sec in sorted(states.items()))
            lines.append(f'    host {host}: {parts}')
    ro = slo.get('rollouts', {})
    lines.append(f'  rollouts            {ro.get("count", 0)} '
                 f'({ro.get("completed", 0)} completed, '
                 f'{ro.get("rollbacks", 0)} rolled back)')
    return lines


def _render_trace(trace):
    lines = [
        'request tracing',
        f'  span trees          {trace["complete_trees"]}/'
        f'{trace["traces"]} complete '
        f'(completeness {trace["completeness_total"]})',
        f'  orphan spans        {trace["orphan_spans"]}',
        f'  retry hops          {trace["retry_hops"]} in-host, '
        f'{trace["redispatch_hops"]} cross-host',
        f'  multi-host traces   {trace["multi_host_traces"]}',
        '  exclusive time by span (ms)',
    ]
    by_name = trace.get('spans_by_name', {})
    for name, agg in sorted(by_name.items(),
                            key=lambda kv: -kv[1].get('exclusive_ms', 0)):
        lines.append(f'    {name:<12} n={agg.get("count", 0):>4}  '
                     f'excl={agg.get("exclusive_ms")}')
    return lines


def main(argv=None):
    args = parse_args(argv)
    records = load_jsonl(args.stream)
    ok = True
    for i, rec in enumerate(records):
        try:
            validate_record(rec)
        except SchemaError as e:
            print(f'FAIL: record {i}: {e}', file=sys.stderr)
            ok = False
    slos = [r for r in records if r.get('kind') == 'slo']
    traces = [r for r in records if r.get('kind') == 'trace']

    if not slos:
        print('FAIL: no slo record in the stream — nothing to '
              'aggregate (run make slo-smoke, or wire an SLOAggregator '
              'into the FleetRouter)', file=sys.stderr)
        ok = False

    lines = [f'== fleet dashboard: {args.stream} ==']
    slo = slos[-1] if slos else None
    trace = traces[-1] if traces else None
    if slo is not None:
        lines += _render_slo(slo)
    if trace is not None:
        lines += _render_trace(trace)
        if trace['orphan_spans'] > 0:
            print(f'FAIL: {trace["orphan_spans"]} orphan span(s) — '
                  f'span parents are missing, the trace trees cannot '
                  f'be trusted', file=sys.stderr)
            ok = False
        if trace['completeness_total'] < 1.0:
            print(f'FAIL: trace completeness '
                  f'{trace["completeness_total"]} < 1.0 '
                  f'({trace["complete_trees"]}/{trace["traces"]} '
                  f'single-root trees)', file=sys.stderr)
            ok = False
    else:
        lines.append('WARNING: no trace record — tracing not armed '
                     '(SLO view only)')

    print('\n'.join(lines))
    dashboard = dict(stream=args.stream, ok=ok,
                     slo=slo, trace=trace)
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(dashboard, f, indent=2)
        print(f'dashboard JSON -> {args.out}')
    if ok:
        print('DASHBOARD OK')
    return 0 if ok else 1


if __name__ == '__main__':
    sys.exit(main())
