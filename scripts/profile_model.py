"""Capture a jax.profiler trace of a training step (xprof/perfetto).

Usage: python scripts/profile_model.py [--out /tmp/se3_trace] [--cpu]
The named_scope labels make every hot region of the trace directly
attributable to a model stage (the authoritative list is
observability.timing.MODEL_SCOPES):

    neighbors / basis / conv_in / trunk / conv_out   model stages
    attention / attn_qkv / attn_core                 attention block
    pallas_attention[_bwd]                           fused attention kernel
    ring_knn                                         sequence-parallel kNN
    ici_wait / exchange                              ring ppermute hop /
                                                     neighbor-sparse gather
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument('--out', default='/tmp/se3_trace')
    ap.add_argument('--cpu', action='store_true')
    ap.add_argument('--nodes', type=int, default=256)
    ap.add_argument('--steps', type=int, default=3)
    args = ap.parse_args()
    if args.steps < 1:
        ap.error('--steps must be >= 1')

    import jax
    if args.cpu:
        jax.config.update('jax_platforms', 'cpu')

    import numpy as np

    from se3_transformer_tpu.training import DenoiseConfig, DenoiseTrainer
    from se3_transformer_tpu.utils.observability import profile_trace

    cfg = DenoiseConfig(num_nodes=args.nodes, batch_size=1, num_degrees=2,
                        max_sparse_neighbors=8)
    trainer = DenoiseTrainer(cfg)
    from se3_transformer_tpu.training.denoise import synthetic_protein_batch
    batch = synthetic_protein_batch(cfg, np.random.RandomState(0))
    trainer.train_step(batch)  # compile outside the trace

    with profile_trace(args.out):
        for _ in range(args.steps):
            loss = trainer.train_step(batch)
        jax.block_until_ready(loss)
    print(f'trace written to {args.out} (open with xprof/tensorboard)')


if __name__ == '__main__':
    main()
