"""Summarize a jax.profiler trace directory: top ops by device time.

Parses the Chrome-trace JSON (trace.json.gz) that jax.profiler writes
under <dir>/plugins/profile/<ts>/ — no tensorboard/xprof needed. Events
on device tracks (TPU/TensorCore pids) are aggregated by op name and
printed as a table with total ms and share, so "what dominates the
step" is one command:

    python scripts/trace_summary.py --dir /tmp/flagship_trace [--top 30]

The name aggregation folds XLA's fusion suffixes (fusion.123 -> fusion)
unless --raw; --match FILTER restricts to names containing FILTER.
"""
import argparse
import glob
import gzip
import json
import os
import re
import sys


def find_trace_file(d):
    pats = [os.path.join(d, 'plugins', 'profile', '*', '*.trace.json.gz'),
            os.path.join(d, '**', '*.trace.json.gz'),
            os.path.join(d, '*.trace.json.gz')]
    hits = []
    for p in pats:
        hits += glob.glob(p, recursive=True)
    if not hits:
        raise FileNotFoundError(f'no *.trace.json.gz under {d}')
    return max(hits, key=os.path.getmtime)


def load_events(path):
    with gzip.open(path, 'rt') as f:
        data = json.load(f)
    return data.get('traceEvents', [])


def device_pids(events):
    """pids whose process name looks like an accelerator/device track
    (covers 'TPU', 'Tensorcore', '/device:...'; falls back to every pid
    that is not explicitly host-side python/runtime)."""
    names = {}
    for ev in events:
        if ev.get('ph') == 'M' and ev.get('name') == 'process_name':
            names[ev['pid']] = ev.get('args', {}).get('name', '')
    dev = {pid for pid, n in names.items()
           if re.search(r'tpu|tensorcore|/device|gpu|accelerator', n,
                        re.IGNORECASE)}
    if not dev:
        dev = {pid for pid, n in names.items()
               if not re.search(r'python|host|plugin|runtime', n,
                                re.IGNORECASE)}
    return dev, names


def fold_name(name):
    # fusion.123 / copy.5 / custom-call.7 -> family; keep pallas kernel
    # names (custom-call targets) intact when present in args
    return re.sub(r'\.\d+$', '', name)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--dir', required=True)
    ap.add_argument('--top', type=int, default=30)
    ap.add_argument('--raw', action='store_true',
                    help='no fusion-suffix folding')
    ap.add_argument('--match', default=None)
    args = ap.parse_args(argv)

    path = find_trace_file(args.dir)
    events = load_events(path)
    dev, names = device_pids(events)

    total = 0.0
    agg = {}
    for ev in events:
        if ev.get('ph') != 'X' or ev.get('pid') not in dev:
            continue
        name = ev.get('name', '?')
        if args.match and args.match not in name:
            continue
        dur = float(ev.get('dur', 0.0)) / 1e3  # us -> ms
        key = name if args.raw else fold_name(name)
        agg[key] = agg.get(key, 0.0) + dur
        total += dur
    rows = sorted(agg.items(), key=lambda kv: -kv[1])[:args.top]
    print(f'# {path}')
    print(f'# device tracks: '
          f'{sorted(names.get(p, str(p)) for p in dev)}')
    print(f'# total device-track time: {total:.1f} ms')
    for name, ms in rows:
        print(f'{ms:10.2f} ms  {100 * ms / total:5.1f}%  {name}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
