"""Summarize a jax.profiler trace directory: top ops by device time.

Thin CLI shim over `observability.profiling` (PR 6 consolidated the
trace parsing there — this module's old inline parser and the retired
`stage_timings.py` are both superseded by per-scope attribution; see
docs/PERFORMANCE.md "Reading rooflines"). Same usage as before:

    python scripts/trace_summary.py --dir /tmp/flagship_trace [--top 30]
        [--raw] [--match FILTER] [--hlo FILE]

With --hlo (a compiled program's `as_text()` dump) the table also
prints the MODEL_SCOPES attribution + coverage for the trace.
Durations are EXCLUSIVE now (nested call/fusion events no longer
double-count), so totals are honest where the old table inflated them.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from se3_transformer_tpu.observability import profiling  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument('--dir', required=True)
    ap.add_argument('--top', type=int, default=30)
    ap.add_argument('--raw', action='store_true',
                    help='no fusion-suffix folding')
    ap.add_argument('--match', default=None)
    ap.add_argument('--hlo', default=None,
                    help='compiled HLO text file: also attribute device '
                         'time onto MODEL_SCOPES')
    args = ap.parse_args(argv)

    path = profiling.find_trace_file(args.dir)
    events = profiling.load_trace_events(path)
    dev, info = profiling.device_events(events)
    # one exclusive-duration pass feeds both the op table and the
    # scope attribution (flagship traces run to hundreds of thousands
    # of events)
    pairs = profiling.exclusive_durations(dev)

    rows = profiling.device_time_by_op(dev, raw=args.raw,
                                       match=args.match, pairs=pairs)
    total = sum(ms for _, ms in rows)
    print(f'# {path}')
    print(f'# device tracks ({info["selector"]}): {info["tracks"]}')
    print(f'# total device time (exclusive): {total:.1f} ms')
    for name, ms in rows[:args.top]:
        print(f'{ms:10.2f} ms  {100 * ms / total:5.1f}%  {name}')

    if args.hlo:
        with open(args.hlo) as f:
            op_map = profiling.op_scope_map(f.read())
        att = profiling.attribute_scopes(dev, op_map, pairs=pairs)
        t = att['total_us'] or 1.0
        print(f'# scope attribution (coverage '
              f'{att["attributed_us"] / t:.0%}):')
        for scope, us in sorted(att['scope_us'].items(),
                                key=lambda kv: -kv[1]):
            print(f'{us / 1e3:10.2f} ms  {100 * us / t:5.1f}%  {scope}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
