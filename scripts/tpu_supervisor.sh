#!/bin/bash
# Wait for any in-flight tpu_session (or an already-running retry loop)
# to exit — a blocked waiter may resume when the tunnel returns: never
# kill it, never race it — then keep relaunching fresh sessions until
# one completes with the chip. Log file is the loop's hardcoded
# /tmp/tpu_session_r2.log (keep in sync with tpu_session_loop.sh).
cd /root/repo || exit 1
# single-instance lock: two supervisors waking together would exec two
# session loops and race for the single-client tunnel
LOCK=/tmp/tpu_supervisor.lock
if ! mkdir "$LOCK" 2>/dev/null; then
  echo "[supervisor] another instance holds $LOCK, exiting" >&2
  exit 0
fi
trap 'rmdir "$LOCK" 2>/dev/null' EXIT
LOG=/tmp/tpu_session_r2.log
# only a success logged AFTER this point counts — the log is append-only
# across rounds and an old "session done (ok)" must not suppress a rerun.
# A unique start marker (not line offsets) survives log truncation or
# rotation during the wait (ADVICE r2 #4)
MARK="supervisor-epoch-$$-$(date -u +%s)"
echo "[supervisor] $MARK waiting" >> "$LOG"
while pgrep -f "scripts/tpu_session.py" > /dev/null \
    || pgrep -f "tpu_session_loop.sh" > /dev/null; do
  sleep 60
done
if awk -v m="$MARK" 'index($0, m) {found=1}
                     found && /session done \(ok\)/ {ok=1}
                     END {exit !ok}' "$LOG" 2>/dev/null; then
  echo "[supervisor] session succeeded while we waited, nothing to do" >> "$LOG"
  exit 0
fi
echo "[supervisor] prior session gone, starting loop $(date -u +%H:%M:%S)" >> "$LOG"
# child (not exec): the EXIT trap must release the lock when the loop ends
bash scripts/tpu_session_loop.sh
exit $?
