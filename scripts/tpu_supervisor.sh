#!/bin/bash
# Wait for any in-flight tpu_session (or an already-running retry loop)
# to exit — a blocked waiter may resume when the tunnel returns: never
# kill it, never race it — then keep relaunching fresh sessions until
# one completes with the chip. Log file is the loop's hardcoded
# /tmp/tpu_session_r2.log (keep in sync with tpu_session_loop.sh).
cd /root/repo || exit 1
# STOP-FILE PROTOCOL: .tpu_stop means "shut down the running loop NOW".
# Whoever intentionally STARTS a loop or supervisor clears any stale
# stop first (a leftover from last round must not disable this launch);
# the checks further down react only to a stop that appears WHILE we
# run. Don't touch the stop file in the same breath as launching.
rm -f /root/repo/.tpu_stop
# single-instance lock: two supervisors waking together would exec two
# session loops and race for the single-client tunnel
LOCK=/tmp/tpu_supervisor.lock
if ! mkdir "$LOCK" 2>/dev/null; then
  echo "[supervisor] another instance holds $LOCK, exiting" >&2
  exit 0
fi
trap 'rmdir "$LOCK" 2>/dev/null' EXIT
LOG=/tmp/tpu_session_r2.log
# only a success logged AFTER this point counts — the log is append-only
# across rounds and an old "session done (ok)" must not suppress a rerun.
# A unique start marker (not line offsets) survives log truncation or
# rotation during the wait (ADVICE r2 #4)
MARK="supervisor-epoch-$$-$(date -u +%s)"
echo "[supervisor] $MARK waiting" >> "$LOG"
# .tpu_stop is the round-end clean-shutdown signal (see
# tpu_session_loop.sh): the supervisor must honor it too, or a
# stop-triggered loop exit would just get relaunched here — and the
# relaunched loop's startup rm -f would erase the stop signal
STOP=/root/repo/.tpu_stop
while pgrep -f "scripts/tpu_session.py" > /dev/null \
    || pgrep -f "tpu_session_loop.sh" > /dev/null; do
  if [ -e "$STOP" ]; then
    echo "[supervisor] stop file present, exiting without relaunch" >> "$LOG"
    exit 0
  fi
  sleep 60
done
if [ -e "$STOP" ]; then
  echo "[supervisor] stop file present, exiting without relaunch" >> "$LOG"
  exit 0
fi
if awk -v m="$MARK" 'index($0, m) {found=1}
                     found && /session done \(ok\)/ {ok=1}
                     END {exit !ok}' "$LOG" 2>/dev/null; then
  echo "[supervisor] session succeeded while we waited, nothing to do" >> "$LOG"
  exit 0
fi
echo "[supervisor] prior session gone, starting loop $(date -u +%H:%M:%S)" >> "$LOG"
# child (not exec): the EXIT trap must release the lock when the loop ends
bash scripts/tpu_session_loop.sh
exit $?
