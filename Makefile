# Common entry points (see README.md for details)
.PHONY: test test-fast bench denoise cookbook molecular profile tpu-checks obs-smoke serve-smoke serve-multi-smoke serve-fleet-smoke slo-smoke transport-smoke pipeline-smoke tune-smoke ring-smoke profile-smoke so2-smoke v2-smoke flash-smoke assembly-smoke mesh-smoke chaos-smoke train-chaos-smoke quant-smoke perf-gate clean-cache

test:              ## full suite on the simulated 8-device CPU mesh
	python -m pytest tests/ -q

test-fast:         ## <5-min single-core gate: kernel/math numerics + model smokes (skips slow + heavy tiers)
	python -m pytest tests/ -q -m "not slow and not heavy"

test-heavy:        ## the compile-heavy model-level integration tier
	python -m pytest tests/ -q -m "heavy"

bench:             ## one-line JSON benchmark (TPU if available, CPU fallback)
	python bench.py

denoise:           ## denoise training example
	python denoise.py --steps 20

cookbook:          ## every reference README usage pattern
	python examples/cookbook.py

molecular:         ## edge-conditioned molecular training example
	python examples/molecular_property.py

profile:           ## capture an xprof trace of a training step
	python scripts/profile_model.py --cpu

obs-smoke:         ## 3-step CPU denoise with telemetry: schema-gates the JSONL, renders the report (docs/OBSERVABILITY.md)
	python denoise.py --steps 3 --nodes 48 --accum 2 --cpu --telemetry --flush-every 2 --metrics /tmp/obs_smoke.jsonl
	python scripts/obs_report.py /tmp/obs_smoke.jsonl --validate --out /tmp/obs_smoke_summary.json

serve-smoke:       ## 3-request CPU serving run (2 buckets + 1 oversize reject): exits non-zero unless the telemetry stream is schema-valid AND zero post-warmup compiles fired
	rm -f /tmp/serve_smoke.jsonl
	python scripts/serve.py --requests 3 --oversize 1 --buckets 12,24 --batch-size 2 --cpu --metrics /tmp/serve_smoke.jsonl --out /tmp/serve_smoke_summary.json

serve-multi-smoke: ## 2-replica CPU continuous-batching gate: >=1 admission into an in-flight bucket slot, one mid-run rolling weight swap with zero dropped requests and zero post-warmup compiles, schema-valid stream (--require serve), and the serve perf budgets
	rm -f /tmp/serve_multi_smoke.jsonl
	python scripts/serve.py --replicas 2 --requests 16 --oversize 1 --swap-at 8 --buckets 12,24 --batch-size 2 --max-wait-ms 50 --cpu --metrics /tmp/serve_multi_smoke.jsonl --out /tmp/serve_multi_smoke_summary.json
	python scripts/obs_report.py /tmp/serve_multi_smoke.jsonl --validate --require serve --out /tmp/serve_multi_report.json
	python scripts/perf_gate.py /tmp/serve_multi_smoke.jsonl

pipeline-smoke:    ## 6-step pipelined CPU denoise (docs/PERFORMANCE.md): exits non-zero on schema violation or a 100% prefetch-stall rate
	rm -f /tmp/pipeline_smoke.jsonl
	python denoise.py --steps 6 --nodes 48 --accum 2 --cpu --pipelined --telemetry --flush-every 3 --metrics /tmp/pipeline_smoke.jsonl
	python scripts/obs_report.py /tmp/pipeline_smoke.jsonl --validate --require-pipeline --out /tmp/pipeline_smoke_summary.json

tune-smoke:        ## interpret-mode kernel-autotuner mini-sweep on CPU (docs/PERFORMANCE.md "Kernel tuning"): exits non-zero unless the tune records are schema-valid AND a promoted entry is consulted on the next pick
	rm -rf /tmp/tune_smoke_cache /tmp/tune_smoke.jsonl
	SE3_TPU_CACHE_PATH=/tmp/tune_smoke_cache python scripts/tune_kernels.py --smoke --dry-run --max-targets 2 --out /tmp/tune_smoke.jsonl
	SE3_TPU_CACHE_PATH=/tmp/tune_smoke_cache python scripts/tune_kernels.py --smoke --max-targets 1 --max-candidates 1 --pairs 1 --steps 2 --margin -1 --out /tmp/tune_smoke.jsonl
	python scripts/obs_report.py /tmp/tune_smoke.jsonl --validate --require-tune --out /tmp/tune_smoke_summary.json

ring-smoke:        ## virtual-8-device sequence-parallel comm gate (docs/PERFORMANCE.md "Sequence-parallel comms"): exchange-vs-dense parity + schema'd comm records + no full-width all-gather in the traced sp>1 exchange program
	rm -f /tmp/ring_smoke.jsonl
	python scripts/ring_smoke.py --metrics /tmp/ring_smoke.jsonl
	python scripts/obs_report.py /tmp/ring_smoke.jsonl --validate --require-comm --out /tmp/ring_smoke_summary.json

profile-smoke:     ## toy trace -> per-scope device-time attribution (docs/PERFORMANCE.md "Reading rooflines"): exits non-zero unless MODEL_SCOPES cover >=80% of device time AND the cost/profile records are schema-valid
	rm -f /tmp/profile_smoke.jsonl
	python scripts/profile_smoke.py --metrics /tmp/profile_smoke.jsonl --min-coverage 0.8
	python scripts/obs_report.py /tmp/profile_smoke.jsonl --validate --require cost,profile --out /tmp/profile_smoke_summary.json

so2-smoke:         ## CPU so2-backend gate (docs/PERFORMANCE.md "Higher degrees via SO(2) reduction"): dense-vs-so2 parity + so2 equivariance at the swept degrees, schema'd so2_sweep A/B record, judged by the committed degree-4 perf budgets
	rm -f /tmp/so2_smoke.jsonl
	python scripts/so2_smoke.py --metrics /tmp/so2_smoke.jsonl
	python scripts/obs_report.py /tmp/so2_smoke.jsonl --validate --require so2_sweep --out /tmp/so2_smoke_summary.json
	python scripts/perf_gate.py /tmp/so2_smoke.jsonl

v2-smoke:          ## CPU v2 model-family gate (docs/PERFORMANCE.md "When to pick v1-dense / v1-so2 / v2"): SE3TransformerV2 equivariance at the swept degrees + the v2-vs-(v1+so2) family A/B, schema'd v2_sweep record, judged by the committed v2 perf budgets
	rm -f /tmp/v2_smoke.jsonl
	python scripts/v2_smoke.py --metrics /tmp/v2_smoke.jsonl
	python scripts/obs_report.py /tmp/v2_smoke.jsonl --validate --require v2_sweep --out /tmp/v2_smoke_summary.json
	python scripts/perf_gate.py /tmp/v2_smoke.jsonl

flash-smoke:       ## CPU streaming-attention gate (docs/PERFORMANCE.md "Flash equivariant attention"): dense-arm + so2-arm parity vs the unfused path (masked rows, XLA stream AND interpret-mode Pallas kernel), fused equivariance at degrees 2/4, schema'd flash A/B record, judged by the committed step-time + peak-HBM win budgets
	rm -f /tmp/flash_smoke.jsonl
	python scripts/flash_smoke.py --metrics /tmp/flash_smoke.jsonl
	python scripts/obs_report.py /tmp/flash_smoke.jsonl --validate --require flash --out /tmp/flash_smoke_summary.json
	python scripts/perf_gate.py /tmp/flash_smoke.jsonl

assembly-smoke:    ## kNN-free large-assembly serving gate (docs/PERFORMANCE.md "Large assemblies"): global-vs-materialized parity + equivariance<=1e-5 on identical params, n=4096 SERVED through an AOT InferenceEngine global bucket (zero post-warmup compiles, oversize reject carries max_bucket), sp=2 ring arm proven all-gather-free from its partitioned HLO, >=3x streaming-vs-materialized peak-HBM off the cost ledger, schema'd assembly record judged by the committed budgets; then the --inject-regression arm must exit rc==1, proving those budgets fire
	rm -f /tmp/assembly_smoke.jsonl
	python scripts/assembly_smoke.py --metrics /tmp/assembly_smoke.jsonl
	python scripts/obs_report.py /tmp/assembly_smoke.jsonl --validate --require assembly --out /tmp/assembly_smoke_summary.json
	python scripts/perf_gate.py /tmp/assembly_smoke.jsonl
	rm -f /tmp/assembly_inject.jsonl
	python scripts/assembly_smoke.py --metrics /tmp/assembly_inject.jsonl --inject-regression >/tmp/assembly_inject.log 2>&1; test $$? -eq 1 || { echo "assembly-smoke injected arm did NOT fire with rc=1 — a vanished memory win / broken equivariance / unserved bucket went undetected; output:"; cat /tmp/assembly_inject.log; exit 1; }  # rc=1 is the committed budgets FIRING on the corrupted record; any other rc (crash, argparse, rc=2 budgets-not-wired) fails loudly with the evidence

mesh-smoke:        ## composed dp x sp x tp gate (docs/PERFORMANCE.md "Composed parallelism"): one composed (2,2,2) update matches dp-only (2,1,1) on the identical global problem to 1e-5, the flagship ring point compiles all-gather-free on the sequence axis WITH tp live (axis-aware HLO scan), the measured row banks as a schema'd mesh_sweep record (--require mesh_sweep) and the committed per-axis byte / memory / proof-bit budgets judge it; then the --inject-regression arm must exit rc==1, proving those budgets fire
	rm -f /tmp/mesh_smoke.jsonl
	python scripts/mesh_smoke.py --metrics /tmp/mesh_smoke.jsonl
	python scripts/obs_report.py /tmp/mesh_smoke.jsonl --validate --require mesh_sweep --out /tmp/mesh_smoke_summary.json
	rm -f /tmp/mesh_inject.jsonl
	python scripts/mesh_smoke.py --metrics /tmp/mesh_inject.jsonl --inject-regression >/tmp/mesh_inject.log 2>&1; test $$? -eq 1 || { echo "mesh-smoke injected arm did NOT fire with rc=1 — a sequence-rematerializing all-gather / per-axis byte blowup / memory regression went undetected; output:"; cat /tmp/mesh_inject.log; exit 1; }  # rc=1 is the committed budgets FIRING on the corrupted record; any other rc (crash, argparse, rc=2 budgets-not-wired) fails loudly with the evidence

chaos-smoke:       ## fault-domain gate (docs/ROBUSTNESS.md): seeded replica crashes + latency spikes + a torn latest checkpoint + one rolling swap over 3 CPU replicas — zero lost requests, >=1 observed quarantine->recovery, swap restores the FALLBACK step, schema'd fault records (--require fault), judged by the chaos perf budgets; then the WEAKENED arm (a fault class made droppable) must exit rc==1, proving the zero-lost gate fires
	rm -f /tmp/chaos_smoke.jsonl
	python scripts/chaos_smoke.py --metrics /tmp/chaos_smoke.jsonl --out /tmp/chaos_smoke_summary.json
	python scripts/obs_report.py /tmp/chaos_smoke.jsonl --validate --require fault,serve --out /tmp/chaos_smoke_report.json
	python scripts/perf_gate.py /tmp/chaos_smoke.jsonl
	python scripts/chaos_smoke.py --weaken drop >/tmp/chaos_weaken.log 2>&1; test $$? -eq 1 || { echo "chaos-smoke weakened arm did NOT fire with rc=1 — a droppable fault class went undetected; output:"; cat /tmp/chaos_weaken.log; exit 1; }  # rc=1 is the gate FIRING on lost requests; any other rc (crash, argparse) fails loudly with the evidence

serve-fleet-smoke: ## cross-host fleet gate (docs/ROBUSTNESS.md "Fleet fault domain"): 3 CPU host PROCESSES behind a FleetRouter — one SIGKILLed mid-run (requests redispatched cross-host, host quarantined, recovered via half-open probes after restart), seeded transport faults (latency + partition drop), and a poisoned-canary weight rollout that must AUTO-ROLL-BACK with zero sibling swaps — zero lost requests fleet-wide, zero post-warmup compiles, every host exits 0 on graceful SIGTERM, schema'd fleet records (--require fleet) judged by the fleet perf budgets; then the WEAKENED arm (host exclusion nulled) must exit rc==1, proving the gates fire
	rm -f /tmp/fleet_chaos.jsonl
	python scripts/fleet_chaos_smoke.py --metrics /tmp/fleet_chaos.jsonl --out /tmp/fleet_chaos_summary.json
	python scripts/obs_report.py /tmp/fleet_chaos.jsonl --validate --require fleet --out /tmp/fleet_chaos_report.json
	python scripts/perf_gate.py /tmp/fleet_chaos.jsonl
	python scripts/fleet_chaos_smoke.py --weaken noexclude >/tmp/fleet_weaken.log 2>&1; test $$? -eq 1 || { echo "serve-fleet-smoke weakened arm did NOT fire with rc=1 — nulled host exclusion went undetected; output:"; cat /tmp/fleet_weaken.log; exit 1; }  # rc=1 is the gates FIRING on the dead host eating traffic; any other rc (crash, argparse) fails loudly with the evidence

transport-smoke:   ## transport A/B gate (docs/ROBUSTNESS.md "Transport"): the SAME seeded closed-loop workload through legacy connect-per-call JSON vs pooled multiplexed binary framing — zero errors / frame errors / mid-run reconnects, in-flight depth > 1 (--require transport), and the committed QPS floor (>=3x) + p99 + wire-bytes ceilings judge the banked transport record; then the --inject-regression arm must exit rc==1, proving those budgets fire
	rm -f /tmp/transport_ab.jsonl
	python scripts/transport_loadgen.py --metrics /tmp/transport_ab.jsonl
	python scripts/obs_report.py /tmp/transport_ab.jsonl --validate --require transport --out /tmp/transport_ab_report.json
	python scripts/perf_gate.py /tmp/transport_ab.jsonl
	rm -f /tmp/transport_inject.jsonl
	python scripts/transport_loadgen.py --metrics /tmp/transport_inject.jsonl --inject-regression >/tmp/transport_inject.log 2>&1; test $$? -eq 1 || { echo "transport-smoke injected arm did NOT fire with rc=1 — a vanished QPS win / blown p99 / JSON-fat wire went undetected; output:"; cat /tmp/transport_inject.log; exit 1; }  # rc=1 is the committed budgets FIRING on the corrupted record; any other rc (crash, argparse, rc=2 budgets-not-wired) fails loudly with the evidence

slo-smoke:         ## fleet observability gate (docs/OBSERVABILITY.md "Fleet dashboard"): 2 traced in-process hosts under seeded transport faults — every resolved request yields ONE complete single-root span tree (zero orphans), redispatched requests show multi-host traces reconciling with the cross_host_retries counter, merged-histogram fleet percentiles + availability land in schema'd trace/slo records (--require trace,slo), the dashboard renders, and the fleet perf budgets judge the stream; then the --inject-regression arm (fleet-side attempt spans discarded) must exit rc==1, proving the completeness gates fire
	rm -f /tmp/slo_smoke.jsonl
	python scripts/slo_smoke.py --metrics /tmp/slo_smoke.jsonl --out /tmp/slo_smoke_summary.json
	python scripts/obs_report.py /tmp/slo_smoke.jsonl --validate --require trace,slo --out /tmp/slo_smoke_report.json
	python scripts/slo_report.py /tmp/slo_smoke.jsonl --out /tmp/slo_dashboard.json
	python scripts/perf_gate.py /tmp/slo_smoke.jsonl
	python scripts/slo_smoke.py --metrics /tmp/slo_inject.jsonl --inject-regression >/tmp/slo_inject.log 2>&1; test $$? -eq 1 || { echo "slo-smoke injected arm did NOT fire with rc=1 — broken instrumentation (orphaned spans) went undetected; output:"; cat /tmp/slo_inject.log; exit 1; }  # rc=1 is the completeness gate FIRING on orphan spans; any other rc (crash, argparse) fails loudly with the evidence

train-chaos-smoke: ## self-healing training gate (docs/ROBUSTNESS.md "Training fault domain"): an injected-NaN step + a real mid-run SIGTERM over the guarded elastic loop — the run must roll back (>=1 observed), exit resumable, resume, and finish BIT-EXACT vs an uninterrupted control arm with zero post-warmup recompiles; schema'd guard records (--require guard: injections >= 1, diverged == false), judged by the train-chaos perf budgets; then the WEAKENED arm (rollback nulled) must exit rc==1, proving the diverged gate fires
	rm -f /tmp/train_chaos.jsonl
	python scripts/train_chaos_smoke.py --metrics /tmp/train_chaos.jsonl --out /tmp/train_chaos_summary.json
	python scripts/obs_report.py /tmp/train_chaos.jsonl --validate --require guard --out /tmp/train_chaos_report.json
	python scripts/perf_gate.py /tmp/train_chaos.jsonl
	python scripts/train_chaos_smoke.py --weaken norollback >/tmp/train_chaos_weaken.log 2>&1; test $$? -eq 1 || { echo "train-chaos-smoke weakened arm did NOT fire with rc=1 — a nulled rollback went undetected; output:"; cat /tmp/train_chaos_weaken.log; exit 1; }  # rc=1 is the diverged gate FIRING; any other rc (crash, argparse) fails loudly with the evidence

quant-smoke:       ## CPU quantized-serving gate (docs/PERFORMANCE.md "Quantized serving"): fp32 + int8-mix AOT engines from ONE param tree — implementation parity <=1e-4 (padded+unpadded, vs the fp32 reference of the same quantized weights), equivariance-L2 <=1e-4 at degrees 2/4, argument-bytes <=0.6x fp32 off the cost ledger, schema'd quant_ab record banked and judged by the committed quant perf budgets
	rm -f /tmp/quant_smoke.jsonl
	python scripts/quant_smoke.py --metrics /tmp/quant_smoke.jsonl
	python scripts/obs_report.py /tmp/quant_smoke.jsonl --validate --require quant_ab --out /tmp/quant_smoke_summary.json
	python scripts/perf_gate.py /tmp/quant_smoke.jsonl

perf-gate:         ## committed budgets vs the evidence streams (docs/PERFORMANCE.md "The perf gate"): must PASS on the current tree, then must FIRE on an injected synthetic regression
	python scripts/perf_gate.py --fresh-cost /tmp/perf_gate_cost.jsonl
	python scripts/perf_gate.py /tmp/perf_gate_cost.jsonl --inject-regression >/tmp/perf_gate_inject.log 2>&1; test $$? -eq 1 || { echo "perf-gate injection arm did NOT fire with rc=1 — gate output:"; cat /tmp/perf_gate_inject.log; exit 1; }  # rc=1 is the gate FIRING; any other rc (argparse error, crash) fails loudly with the evidence

tpu-checks:        ## on-chip equivariance + kernel numerics/speed gate
	python scripts/tpu_checks.py

tpu-session:       ## full on-chip suite, retried until the chip is free
	bash scripts/tpu_session_loop.sh

clean-cache:       ## wipe the Q_J and jit caches
	rm -rf ~/.cache/se3_transformer_tpu
