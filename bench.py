"""Benchmark: denoise-style training throughput on the flagship config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config follows BASELINE.json's north star (1024 nodes, num_degrees=4,
kNN neighbors) in a denoise.py-scale model. The reference publishes no
benchmark numbers (BASELINE.md: "published": {}), so vs_baseline is
reported against this repo's own first recorded value (RECORD below);
1.0 until a prior record exists.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from se3_transformer_tpu.models.se3_transformer import SE3TransformerModule
from se3_transformer_tpu.parallel.sharding import make_sharded_train_step

# first recorded nodes*steps/sec/chip on TPU v5e-1 (update as it improves)
RECORD = None

NUM_NODES = 1024
NUM_DEGREES = 4
BATCH = 1
NUM_NEIGHBORS = 32
STEPS = 20


def main():
    module = SE3TransformerModule(
        num_tokens=24, dim=8, dim_head=8, heads=2, depth=2,
        attend_self=True, input_degrees=1, num_degrees=NUM_DEGREES,
        output_degrees=2, reduce_dim_out=True, differentiable_coors=True,
        num_neighbors=NUM_NEIGHBORS)

    rng = np.random.RandomState(0)
    seqs = jnp.asarray(rng.randint(0, 24, (BATCH, NUM_NODES)))
    coords = jnp.asarray(np.cumsum(
        rng.normal(size=(BATCH, NUM_NODES, 3)), axis=1), jnp.float32)
    coords = coords - coords.mean(axis=1, keepdims=True)
    masks = jnp.ones((BATCH, NUM_NODES), bool)

    def loss_fn(params, batch, key):
        noise = jax.random.normal(key, batch['coords'].shape,
                                  batch['coords'].dtype)
        noised = batch['coords'] + noise
        out = module.apply({'params': params}, batch['seqs'], noised,
                           mask=batch['masks'], return_type=1)
        loss = (((noised + out) - batch['coords']) ** 2).sum(-1).mean()
        return loss, dict()

    # jit the init: eager init would dispatch thousands of tiny ops through
    # the device tunnel and take minutes at 1024 nodes
    init_fn = jax.jit(module.init, static_argnames=('return_type',))
    params = init_fn(jax.random.PRNGKey(0), seqs, coords, mask=masks,
                     return_type=1)['params']
    optimizer = optax.adam(1e-4)
    opt_state = optimizer.init(params)
    step = make_sharded_train_step(loss_fn, optimizer)

    batch = dict(seqs=seqs, coords=coords, masks=masks)
    key = jax.random.PRNGKey(1)

    # compile + warmup
    params, opt_state, loss, _ = step(params, opt_state, batch, key)
    jax.block_until_ready(loss)

    t0 = time.time()
    for i in range(STEPS):
        key, sub = jax.random.split(key)
        params, opt_state, loss, _ = step(params, opt_state, batch, sub)
    jax.block_until_ready(loss)
    dt = time.time() - t0

    nodes_steps_per_sec = BATCH * NUM_NODES * STEPS / dt
    vs = nodes_steps_per_sec / RECORD if RECORD else 1.0
    print(json.dumps({
        'metric': f'denoise_train_nodes_steps_per_sec_per_chip'
                  f'(n={NUM_NODES},deg={NUM_DEGREES},k={NUM_NEIGHBORS})',
        'value': round(nodes_steps_per_sec, 2),
        'unit': 'nodes*steps/sec/chip',
        'vs_baseline': round(vs, 3),
    }))


if __name__ == '__main__':
    main()
