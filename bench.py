"""Benchmark: denoise-style training throughput on the flagship config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config follows BASELINE.json's north star (1024 nodes, num_degrees=4,
kNN neighbors) in a denoise.py-scale model. The reference publishes no
benchmark numbers (BASELINE.md: "published": {}), so vs_baseline is
reported against this repo's own first recorded value (RECORD below);
1.0 until a prior record exists.

All heavy imports happen inside main() so the multiprocessing spawn child
used by the device probe only sees function definitions.
"""
import json
import multiprocessing
import os
import sys
import time

# nodes*steps/sec/chip anchors on TPU v5e-1, rolled forward each round so
# vs_baseline measures THIS round's progress against the last round's
# banked session records (each path compares against its own record —
# they run different programs). Round-5 session (16:06-17:11Z,
# code_rev 4fff503, BENCH_SESSION.jsonl): conservative 337.07 (the
# idle-host block_ab arm; the bench-stage row was 331.11), fast 536.76.
# ESTIMATOR NOTE: chip timing moved to best-of-two windows this round
# (tunnel noise is one-sided); the fast anchor re-measured 536.94 under
# it — indistinguishable — and both anchors are best *observed* windows,
# so best-of-two vs them carries no built-in tailwind beyond the ~1-2%
# single-session spread. Round-4 anchors were 296.26 / 536.69; round-3
# 262.38 / 309.57.
RECORD = 337.07
FAST_RECORD = 536.76


def _probe_device(q):
    try:
        import jax
        q.put(jax.default_backend())
    except Exception as e:
        q.put(f'error:{type(e).__name__}')


def _probe_once(timeout_s: int):
    """One subprocess probe attempt. Returns (backend, fallback_reason)."""
    ctx = multiprocessing.get_context('spawn')
    q = ctx.Queue()
    p = ctx.Process(target=_probe_device, args=(q,))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        # a child wedged inside the tunnel's backend init can ignore
        # SIGTERM — escalate to SIGKILL rather than joining forever
        p.terminate()
        p.join(10)
        if p.is_alive():
            p.kill()
            p.join(10)
        return 'cpu', f'probe_timeout_{timeout_s}s'
    try:
        backend = q.get(timeout=5)
    except Exception:
        return 'cpu', 'probe_died_no_result'
    if backend.startswith('error:'):
        return 'cpu', f'probe_{backend}'
    if backend == 'cpu':
        return 'cpu', 'no_accelerator_registered'
    return backend, None


def _device_backend_or_cpu(timeouts=(120, 240, 600), sleep_s: int = 30):
    """Probe the accelerator backend in a subprocess (the axon TPU tunnel
    is single-client and can wedge at backend init if a previous holder
    died), falling back to CPU with an honest metric label.

    Retries with escalating timeouts (VERDICT r4 next #1): the observed
    round-4 failure was a single 120 s probe losing to a cold tunnel —
    round-4's successful session acquired the chip in 8 s once granted,
    but a tunnel mid-recovery (or draining another client's lease) takes
    minutes. Before the first attempt, .tpu_stop is touched so any
    WAITING scripts/tpu_session_loop.sh stands down (a blocked waiter
    holds no claim but a freshly-granted lease would starve this probe;
    the loop's watchdog exits waiters within ~35 s of the touch). A
    claim-HOLDING session finishes its stages and releases on its own —
    the escalating window (~17 min total) is sized to outlive a focused
    session's remaining stages.

    Returns (backend, fallback_reason). Any backend other than 'cpu' is
    accepted as the chip — the driver environment registers the TPU
    behind a plugin platform that may NOT be named 'tpu' (r03 tail shows
    "Platform 'axon'"), and a name whitelist here silently forfeited the
    chip three rounds in a row (VERDICT r3 missing #1). fallback_reason
    distinguishes probe timeout / import error / genuinely-cpu so a CPU
    record is diagnosable from the JSON alone (VERDICT r3 weak #2)."""
    # ask any session loop to stand down for the whole capture window; the
    # loop deletes the file at its next launch so this cannot disable a
    # future round's loop (tpu_session_loop.sh header). A KEEPALIVE thread
    # re-touches every 15 s: a loop launched at any point mid-window
    # erases the file at startup (rm -f) and its fresh lease would starve
    # the remaining attempts — per-attempt touches still left the longest
    # (600 s) attempt uncovered. SE3_TPU_STOP_FILE matches tpu_session's
    # test-scratch override; SE3_TPU_BENCH_NO_STOP=1 is for in-round
    # testing, where touching the real stop file would kill the builder's
    # own waiting loop.
    import threading
    stop_path = os.environ.get('SE3_TPU_STOP_FILE') or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), '.tpu_stop')
    probing_done = threading.Event()

    def keep_stood_down():
        while not probing_done.is_set():
            try:
                with open(stop_path, 'w'):
                    pass
            except OSError:
                pass
            probing_done.wait(15)

    if os.environ.get('SE3_TPU_BENCH_NO_STOP') != '1':
        threading.Thread(target=keep_stood_down, daemon=True).start()
    try:
        reason = 'probe_not_attempted'
        for i, t in enumerate(timeouts):
            backend, reason = _probe_once(t)
            if backend != 'cpu':
                return backend, None
            if reason == 'no_accelerator_registered' or \
                    'ModuleNotFoundError' in reason or \
                    'ImportError' in reason:
                # the plugin answered and said cpu, or jax itself is
                # absent — deterministic, retrying won't grow a TPU
                return 'cpu', reason
            if i + 1 < len(timeouts):
                print(f'device probe attempt {i + 1}/{len(timeouts)} failed '
                      f'({reason}); retrying in {sleep_s}s', file=sys.stderr)
                time.sleep(sleep_s)
        return 'cpu', reason + f'_after_{len(timeouts)}_attempts'
    finally:
        probing_done.set()


# what a bare `python bench.py` runs: False = conservative path,
# True = perf knobs, 'auto' = try fast, fall back to the conservative
# path if the fast path RAISES (a wedged tunnel hangs either path — the
# subprocess probe above guards init, the driver's own timeout guards
# the rest). 'auto' since round-3 session 5: the fast path validated on
# hardware END TO END — 309.57 nodes*steps/s vs 262.38 conservative
# (+18%), kernel_smoke bx + radial_bf16 canaries green on chip.
DEFAULT_MODE = 'auto'


def main(backend: str, fast=None, fast_fallback=False, fallback_reason=None,
         pipelined=False):
    """fast=True enables the validated perf knobs (shared radial trunk,
    basis-fused Pallas kernel, bf16 radial) — same model family, same
    training task. Accuracy evidence: equivariance_l2 is measured on
    CPU runs (and on TPU with SE3_TPU_BENCH_EQ=1); default TPU runs
    record None and rely on scripts/tpu_checks.py's on-chip gate
    (3.66e-07 @ f32, radial_bf16 3.07e-07) because the second
    full-flagship f32 compile repeatedly wedged the tunnel. fast='auto'
    tries the fast path and falls back to the conservative one on any
    failure (record flagged fast_fallback). Default: the
    SE3_TPU_BENCH_FAST env var ('1'/'true'/'auto'/...), else
    DEFAULT_MODE.

    pipelined=True (`python bench.py --pipelined`) measures a DIFFERENT
    program from the records above: host batches are REBUILT every step
    (the synchronous records reuse one fixed device batch, i.e. zero
    host batch-build time) and the run compares a synchronous
    build->transfer->step loop against the training.pipeline overlapped
    path (BatchProducer thread + device_prefetch) on the SAME
    executable. The record's value is the pipelined rate; it carries
    the sync arm's rate, a `pipeline` payload (prefetch hits/stalls,
    producer-bound vs device-bound verdict — same shape as the schema'd
    pipeline JSONL record), and never compares against the synchronous
    RECORD anchors."""
    import jax

    # any accelerator name counts as the chip (axon/tpu/...); only 'cpu'
    # is the liveness fallback (VERDICT r3 missing #1)
    on_chip = backend != 'cpu'

    if fast is None:
        env = os.environ.get('SE3_TPU_BENCH_FAST', '').lower()
        fast = 'auto' if env == 'auto' else (
            env in ('1', 'true', 'yes', 'on') if env else DEFAULT_MODE)

    if fast == 'auto':
        try:
            return main(backend, fast=True, fallback_reason=fallback_reason,
                        pipelined=pipelined)
        except Exception:  # noqa: BLE001 - any fast-path failure
            import traceback
            traceback.print_exc(file=sys.stderr)
            print('fast path failed (traceback above); falling back to '
                  'the conservative path', file=sys.stderr)
            # fast_fallback marks the record — a silent conservative
            # record could be misread downstream as a normal fast run
            # (ADVICE r2 #3)
            return main(backend, fast=False, fast_fallback=True,
                        fallback_reason=fallback_reason,
                        pipelined=pipelined)

    if not on_chip:
        # NOTE: setting the JAX_PLATFORMS env var here is too late — the
        # environment's sitecustomize imports jax internals at interpreter
        # startup, freezing the env-derived config. Only the config.update
        # path actually switches the platform.
        jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np
    import optax

    from se3_transformer_tpu.models.se3_transformer import SE3TransformerModule
    from se3_transformer_tpu.parallel.sharding import make_sharded_train_step
    from se3_transformer_tpu.training import recipes
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    from se3_transformer_tpu.utils.helpers import fetch_sync

    enable_compilation_cache()

    # kernel-tuning consult delta: which block picks this record's
    # executables resolved from the measured table vs the heuristic
    # (kernels/tuning.py). Snapshot/delta, not reset: an in-process
    # session (tpu_session) runs several stages off one consult log.
    # The kernel jit caches must be dropped first: picks resolve at
    # trace time, so a kernel already traced by an earlier stage (e.g.
    # the tune stage's adoption proof) would reuse its executable and
    # record NOTHING here — a record benched under tuned blocks
    # masquerading as consult-free.
    from se3_transformer_tpu.kernels import tuning as kernel_tuning
    kernel_tuning.clear_kernel_caches()
    tuning_snap = kernel_tuning.snapshot()

    if on_chip:
        # the tracked config (BASELINE.md): SE3Transformer flagship at
        # 1024 nodes, num_degrees=4, kNN k=32. dim=64 is the max width
        # that fits one v5e at this node count (recipes.py); a toy-width
        # body cannot demonstrate MXU utilization (VERDICT r2 #4).
        # SE3_TPU_BENCH_BATCH raises the per-step batch (per-chip
        # throughput scales with batch while HBM lasts; the reference's
        # own training aggregates 16 micro-batches, denoise.py:13,55) —
        # the metric label carries b= when != 1.
        num_nodes, num_degrees, batch, num_neighbors, steps = 1024, 4, 1, 32, 20
        batch = int(os.environ.get('SE3_TPU_BENCH_BATCH', batch))
        dim = 64
        recipe_name = 'flagship_fast' if fast else 'flagship'
        # SE3_TPU_BENCH_CHUNKS overrides the recipe's edge_chunks (0 =
        # unchunked). Used by the session's batched record so the bench
        # runs the SAME chunk setting the probe measured as fitting for
        # the elected batch (a b>1 that fits chunked can OOM unchunked);
        # the label carries ec= whenever the override is set, so an
        # overridden record is always distinguishable from a bare run.
        chunk_env = os.environ.get('SE3_TPU_BENCH_CHUNKS', '')
        overrides = dict(output_degrees=2, reduce_dim_out=True)
        if chunk_env != '':
            overrides['edge_chunks'] = int(chunk_env) or None
        # SE3_TPU_BENCH_REMAT overrides the reversible remat policy
        # (e.g. 'save_conv_outputs' — the backward replay then skips the
        # dominant radial contraction, ops/trunk.py; 'none' forces the
        # policy OFF, the control arm now that the flagship_fast recipe
        # defaults it on). Labelled rp= so an overridden record never
        # masquerades as the recipe default.
        remat_env = os.environ.get('SE3_TPU_BENCH_REMAT', '')
        if remat_env:
            overrides['remat_policy'] = (
                None if remat_env.lower() == 'none' else remat_env)
        # SE3_TPU_BENCH_CB16=1 turns on conv_bf16 (bf16 STORAGE of the
        # equivariant kernel operands — ops/conv.py): the round-5 A/B
        # knob for the bandwidth-bound contraction. Labelled cb16 so the
        # record never masquerades as the recipe default; the equivariance
        # cost (~1e-3 expected) is the tradeoff being measured.
        cb16 = os.environ.get('SE3_TPU_BENCH_CB16', '').lower() \
            in ('1', 'true', 'yes', 'on')
        if cb16:
            overrides['conv_bf16'] = True
        # vector head for the denoise objective: the recipe default
        # output_degrees=1 is scalar-out (return_type coerced to 0)
        module = recipes.RECIPES[recipe_name](dim=dim, **overrides)
        num_degrees = module.num_degrees
        label = f'{recipe_name},dim={dim},depth={module.depth}' + (
            f',b={batch}' if batch != 1 else '') + (
            f',ec={int(chunk_env)}' if chunk_env != '' else '') + (
            f',rp={remat_env}' if remat_env else '') + (
            ',cb16' if cb16 else '')
    else:
        # liveness fallback only (wedged/absent TPU): tiny config so the
        # bench still completes and is honestly labelled backend=cpu.
        # FROZEN DEFINITION (VERDICT r3 weak #5): this branch runs the
        # exact r03 toy program — fast knobs pinned as an explicit dict
        # (decoupled from whatever 'fast' means in future recipes),
        # steps=10, label 'toy,dim=8,depth=2' + ',fast' — so the CPU
        # trend metric stays comparable round over round. The caller's
        # `fast` is deliberately ignored — EXCEPT after a fast_fallback
        # (the pinned program itself raised): then run knob-free so the
        # bench still emits a record, flagged fast_fallback.
        fast = not fast_fallback
        num_nodes, num_degrees, batch, num_neighbors, steps = 128, 2, 1, 8, 10
        perf = dict(shared_radial_hidden=True, fuse_basis=True,
                    radial_bf16=True) if fast else dict()
        module = SE3TransformerModule(
            num_tokens=24, dim=8, dim_head=8, heads=2, depth=2,
            attend_self=True, input_degrees=1, num_degrees=num_degrees,
            output_degrees=2, reduce_dim_out=True, differentiable_coors=True,
            num_neighbors=num_neighbors, **perf)
        label = 'toy,dim=8,depth=2'

    rng = np.random.RandomState(0)
    if on_chip:
        # flagship takes continuous degree-0 features (no token table)
        seqs = jnp.asarray(rng.normal(size=(batch, num_nodes, dim)),
                           jnp.float32)
    else:
        seqs = jnp.asarray(rng.randint(0, 24, (batch, num_nodes)))
    coords = jnp.asarray(np.cumsum(
        rng.normal(size=(batch, num_nodes, 3)), axis=1), jnp.float32)
    coords = coords - coords.mean(axis=1, keepdims=True)
    masks = jnp.ones((batch, num_nodes), bool)

    def loss_fn(params, data, key):
        noise = jax.random.normal(key, data['coords'].shape,
                                  data['coords'].dtype)
        noised = data['coords'] + noise
        out = module.apply({'params': params}, data['seqs'], noised,
                           mask=data['masks'], return_type=1)
        loss = (((noised + out) - data['coords']) ** 2).sum(-1).mean()
        return loss, dict()

    # jit the init: eager init would dispatch thousands of tiny ops through
    # the device tunnel and take minutes at 1024 nodes
    init_fn = jax.jit(module.init, static_argnames=('return_type',))
    params = init_fn(jax.random.PRNGKey(0), seqs, coords, mask=masks,
                     return_type=1)['params']
    optimizer = optax.adam(1e-4)
    opt_state = optimizer.init(params)
    step = make_sharded_train_step(loss_fn, optimizer)

    data = dict(seqs=seqs, coords=coords, masks=masks)
    key = jax.random.PRNGKey(1)

    # AOT-compile once: the same executable serves the FLOP count (MFU
    # estimate), the cost ledger, and the benchmark loop —
    # lower().compile() does not populate the jit cache, so executing
    # `step` afterwards would compile the multi-minute flagship program
    # a second time
    step_flops = None
    cost_body = None
    exec_fn = step
    try:
        compiled = step.lower(params, opt_state, data, key).compile()
        exec_fn = compiled
        from se3_transformer_tpu.observability.costs import cost_payload
        cost_body = cost_payload(compiled, label=label)
        step_flops = cost_body['flops'] \
            if cost_body['source'] == 'cost_analysis' else None
    except Exception as e:
        # the ledger must never cost the timing: a cost/introspection
        # failure falls back to the uninstrumented jit path
        print(f'bench: cost introspection unavailable '
              f'({type(e).__name__}: {e})', file=sys.stderr)

    # warmup (fetch_sync: an early-returning block here would leak
    # warmup work into the timed window)
    params, opt_state, loss, _ = exec_fn(params, opt_state, data, key)
    fetch_sync(loss)

    # retrace watchdog (observability.runtime): arm on the warmed-up
    # trace cache; any post-warmup retrace marks the record — a silent
    # recompile inside a timed window is exactly the class of artifact
    # the loss-trajectory check cannot see
    from se3_transformer_tpu.observability import RetraceWatchdog
    watchdog = RetraceWatchdog({'train_step': step})
    watchdog.check()  # first check arms

    # keep dispatch async (block only at the end — same timing semantics
    # as before) but RETAIN every step's loss: the 19:29Z session record
    # measured an impossible 411 ms conservative step and the losses
    # that would have exposed (or exonerated) it were discarded. The
    # trajectory now travels with the record.
    # Two timed windows, rate from the BEST one: per-step dispatch rides
    # the device tunnel, whose latency spikes are strictly additive —
    # min-over-windows removes one-sided noise (the 16:57Z rehearsal
    # measured 519 on code that benched 537 in-session minutes earlier).
    # Both window rates travel with the record. Training state carries
    # across windows, so the loss trajectory spans all 2*steps steps.
    losses = []
    window_rates = []
    pipeline_snapshot = None
    sync_rate = None
    if pipelined:
        # ---- pipelined data-path A/B -------------------------------- #
        # Different program from the fixed-batch records: host batches
        # are REBUILT per step in both arms, so the comparison isolates
        # the overlap (producer thread + device prefetch) from the host
        # work itself. Both arms run the SAME compiled executable (no
        # second compile on chip) and two windows each, best-of-window
        # (the established one-sided-noise estimator).
        from se3_transformer_tpu.training.pipeline import (
            BatchProducer, PipelineStats, device_prefetch,
        )

        host_rng = np.random.RandomState(7)

        def host_batch(_i):
            if on_chip:
                s = host_rng.normal(size=(batch, num_nodes, dim)) \
                    .astype(np.float32)
            else:
                s = host_rng.randint(0, 24, (batch, num_nodes)) \
                    .astype(np.int32)
            c = np.cumsum(host_rng.normal(size=(batch, num_nodes, 3)),
                          axis=1).astype(np.float32)
            c -= c.mean(axis=1, keepdims=True)
            return dict(seqs=s, coords=c,
                        masks=np.ones((batch, num_nodes), bool))

        def run_window(batches_iter):
            nonlocal params, opt_state, key
            win_losses = []
            t0 = time.monotonic()
            n = 0
            for b in batches_iter:
                key, sub = jax.random.split(key)
                params, opt_state, loss, _ = exec_fn(params, opt_state,
                                                     b, sub)
                win_losses.append(loss)
                n += 1
            # same window-close semantics as the synchronous bench:
            # host-materialize the chain tail, then stop the clock
            last = float(win_losses[-1])
            fetch_sync(min(jax.tree_util.tree_leaves(params),
                           key=lambda l: l.size))
            dt_w = time.monotonic() - t0
            losses.extend([float(l) for l in win_losses[:-1]] + [last])
            return batch * num_nodes * n / dt_w

        sync_rates, pipe_rates = [], []
        for _ in range(2):
            sync_rates.append(run_window(
                {k: jnp.asarray(v) for k, v in host_batch(i).items()}
                for i in range(steps)))
        stats = PipelineStats(depth=2, capacity=4)
        for _ in range(2):
            with BatchProducer((host_batch(i) for i in range(steps)),
                               capacity=4) as producer:
                pipe_rates.append(run_window(device_prefetch(
                    producer, depth=2, stats=stats)))
        sync_rate = max(sync_rates)
        window_rates = pipe_rates
        nodes_steps_per_sec = max(pipe_rates)
        pipeline_snapshot = stats.snapshot()
        label += ',pipelined'
    # the CPU liveness-fallback toy keeps its FROZEN single-window
    # definition (round-over-round trend comparability); only chip
    # records get the best-of-two estimator. Gate on on_chip (which
    # selected the program being timed), not the in-process backend —
    # a cpu-probed run can still find an accelerator in process (see
    # the eq-twin guard below) but it measured the TOY workload
    n_windows = 0 if pipelined else (2 if on_chip else 1)
    for _ in range(n_windows):
        win_losses = []
        try:
            t0 = time.monotonic()
            for _ in range(steps):
                key, sub = jax.random.split(key)
                params, opt_state, loss, _ = exec_fn(
                    params, opt_state, data, sub)
                win_losses.append(loss)
            # close the window by HOST-MATERIALIZING the chain tail, not
            # block_until_ready: the axon runtime returned from block tens
            # of seconds early on fresh programs (utils.helpers.fetch_sync),
            # which produced two impossible records (411/401 ms "steps")
            # before the loss trajectory exposed it. Only the TAIL is
            # fetched inside the window (final loss gates the last forward,
            # one small param leaf gates the optimizer tail) — fetching
            # every loss here would add a tunnel round-trip per step to dt;
            # the earlier losses are floated after the clock stops.
            last = float(win_losses[-1])
            fetch_sync(min(jax.tree_util.tree_leaves(params),
                           key=lambda l: l.size))
            dt = time.monotonic() - t0
            losses += [float(l) for l in win_losses[:-1]] + [last]
            window_rates.append(batch * num_nodes * steps / dt)
        except Exception as e:
            # a tunnel death here must not lose a window already measured
            # (the round-3 session lost a complete 20-step run exactly this
            # way); the truncated record shows len(window_rates)==1
            print(f'bench: timing window {len(window_rates) + 1} failed '
                  f'({type(e).__name__}: {e})', file=sys.stderr)
            if not window_rates:
                raise
            break

    if not pipelined:
        nodes_steps_per_sec = max(window_rates)
    dt = batch * num_nodes * steps / nodes_steps_per_sec

    # post-window watchdog snapshot: retrace count + device memory
    # (guarded — a diagnostics failure must not lose the timing)
    retrace_post_warmup = None
    hbm_peak_bytes = None
    try:
        snap = watchdog.check()
        retrace_post_warmup = len(snap['retraced'])
        if snap.get('memory'):
            hbm_peak_bytes = snap['memory'].get('peak_bytes_in_use')
    except Exception as e:  # noqa: BLE001
        print(f'watchdog snapshot failed ({type(e).__name__}: {e})',
              file=sys.stderr)

    # equivariance L2 error of the trained model (the BASELINE metric's
    # second component). Guarded: this is a SECOND multi-minute compile
    # over the tunnel, and a tunnel death here must not lose the timing
    # already measured (round-3 session 4 lost a complete 20-step run
    # exactly this way)
    eq_err = None
    eq_scope = None
    eq_env = os.environ.get('SE3_TPU_BENCH_EQ', '').lower()
    # On TPU, full-flagship equivariance is a SECOND multi-minute compile
    # at f32 matmul precision, and it wedged the tunnel for ~25 min in
    # all five round-3 attempts (the timing record survives only thanks
    # to the guard) — opt into it with SE3_TPU_BENCH_EQ=1. The DEFAULT
    # chip record instead measures a reduced-width twin of the same
    # recipe (small compiles proved tunnel-safe across all round-3
    # sessions: scripts/tpu_checks.py ran 5+ of them per session), so
    # the official record carries a non-null equivariance_l2 (VERDICT r3
    # missing #5), labelled with its scope. SE3_TPU_BENCH_EQ=0 skips
    # both (probe-style runs).
    from se3_transformer_tpu.utils.validation import equivariance_l2
    if eq_env in ('1', 'true', 'yes', 'on') \
            or (jax.default_backend() == 'cpu'
                and eq_env not in ('0', 'false', 'no', 'off')):
        try:
            eq_err = equivariance_l2(module, params, seqs, coords, masks)
        except Exception as e:  # noqa: BLE001
            print(f'equivariance check failed ({type(e).__name__}); '
                  f'recording throughput without it', file=sys.stderr)
    elif on_chip and eq_env not in ('0', 'false', 'no', 'off'):
        # on_chip guard: the twin belongs to the flagship branch only —
        # a cpu-probed run that nonetheless finds an accelerator in
        # process measured the TOY workload, and recipe_name is unset
        try:
            # the twin must run the SAME precision knobs as the recorded
            # program: a cb16 record with an f32 twin would hide the
            # ~1e-3 equivariance cost the A/B arm exists to measure
            twin = recipes.RECIPES[recipe_name](
                dim=16, depth=2, num_neighbors=8, output_degrees=2,
                reduce_dim_out=True,
                **({'conv_bf16': True} if cb16 else {}))
            t_n = 128
            t_feats = jnp.asarray(rng.normal(size=(1, t_n, 16)), jnp.float32)
            t_coors = jnp.asarray(rng.normal(size=(1, t_n, 3)) * 2,
                                  jnp.float32)
            t_mask = jnp.ones((1, t_n), bool)
            t_params = jax.jit(twin.init, static_argnames=('return_type',))(
                jax.random.PRNGKey(0), t_feats, t_coors, mask=t_mask,
                return_type=1)['params']
            eq_err = equivariance_l2(twin, t_params, t_feats, t_coors, t_mask)
            eq_scope = f'reduced_twin({recipe_name},dim=16,depth=2,' \
                       f'deg={twin.num_degrees},n={t_n},k=8' \
                       f'{",cb16" if cb16 else ""})'
        except Exception as e:  # noqa: BLE001
            print(f'twin equivariance check failed ({type(e).__name__}); '
                  f'recording throughput without it', file=sys.stderr)

    actual = jax.default_backend()
    actual_chip = actual != 'cpu'
    try:
        device_kind = jax.devices()[0].device_kind if actual_chip else None
    except Exception:
        device_kind = None
    # RECORD/FAST_RECORD and the 197 TFLOP/s peak are TPU v5e numbers:
    # only apply them when the accelerator actually is a TPU (the axon
    # plugin platform name isn't 'tpu', so check device_kind too) — on
    # any other accelerator the ratios would be fabricated
    is_tpu = actual_chip and (actual in ('tpu', 'axon')
                              or 'tpu' in (device_kind or '').lower())
    # each path compares against its own TPU flagship record (different
    # programs); a CPU fallback or batch!=1 run measures a different
    # workload, so comparing would fabricate a regression/speedup
    # pipelined records measure a different program (per-step host batch
    # rebuild) — comparing them to the fixed-batch anchors would
    # fabricate a regression, so they self-compare against their own
    # sync arm instead (pipelined_vs_sync below)
    ref = FAST_RECORD if fast else RECORD
    vs = nodes_steps_per_sec / ref \
        if (ref and is_tpu and batch == 1 and not pipelined) else 1.0
    record = {
        'metric': f'denoise_train_nodes_steps_per_sec_per_chip'
                  f'({label},n={num_nodes},deg={num_degrees},'
                  f'k={num_neighbors},'
                  f'backend={actual}{",fast" if fast else ""})',
        'value': round(nodes_steps_per_sec, 2),
        'unit': f'nodes*steps/sec/{"chip" if actual_chip else "cpu-host"}',
        'vs_baseline': round(vs, 3),
        'equivariance_l2': eq_err,
        'step_ms': round(dt / steps * 1e3, 2),
        'window_rates': [round(r, 2) for r in window_rates],
        # optimizer steps the loss trajectory spans (2*steps once both
        # windows complete) — keeps loss_last comparable across rounds
        # whose window counts differ
        'steps_trained': len(losses),
        # the estimator, explicit (ADVICE r5 #1): cross-round comparisons
        # must never infer it from len(window_rates)
        'timing': ('best-of-2' if len(window_rates) >= 2
                   else 'single-window-truncated')
        if (on_chip or pipelined) else 'frozen-toy',
    }
    try:
        # adopted-vs-heuristic block picks travel with the number: a
        # record benched under a tuned table entry must never be read as
        # a heuristic-pick measurement (kernels/tuning.py)
        record['kernel_tuning'] = kernel_tuning.consult_summary(
            kernel_tuning.consults_since(tuning_snap))
    except Exception as e:  # noqa: BLE001 - diagnostics must not lose
        # the timing already measured
        print(f'kernel tuning summary failed ({type(e).__name__}: {e})',
              file=sys.stderr)
    if pipelined:
        record['mode'] = 'pipelined'
        # same payload shape as the schema'd `pipeline` JSONL record:
        # the proof of where a step's time went travels with the number
        record['pipeline'] = pipeline_snapshot
        record['sync_nodes_steps_per_sec'] = round(sync_rate, 2)
        record['pipelined_vs_sync'] = round(
            nodes_steps_per_sec / sync_rate, 3)
    if retrace_post_warmup is not None:
        # 0 on a healthy run; >0 means a window paid a recompile and the
        # timing is suspect (the watchdog also warned on stderr)
        record['retrace_post_warmup'] = retrace_post_warmup
    if hbm_peak_bytes is not None:
        record['hbm_peak_bytes'] = hbm_peak_bytes
    if cost_body is not None:
        # the schema'd `cost` payload (observability.costs): the
        # BENCH_*.json trajectory tracks peak memory alongside
        # nodes*steps/s, and scripts/perf_gate.py budgets both.
        # peak_hbm_bytes is XLA's static argument+output+temp estimate;
        # hbm_peak_bytes above stays the watchdog's MEASURED figure
        # where the backend reports one. The label is re-stamped here
        # because the pipelined arm appends ',pipelined' AFTER the
        # ledger captured the base label — a cost record must name the
        # arm it measured
        cost_body['label'] = label
        record['cost'] = cost_body
        record['peak_hbm_bytes'] = cost_body['peak_bytes']
    # loss-trajectory sanity: adam at 1e-4 on this objective decreases
    # monotonically-ish from the first step; a flat or garbage sequence
    # means the executable did not run the program the label claims.
    # Shared definition with run_baselines (utils.helpers)
    from se3_transformer_tpu.utils.helpers import loss_trajectory_fields
    record.update(loss_trajectory_fields(losses))
    if eq_scope:
        record['equivariance_scope'] = eq_scope
    if device_kind:
        # prove the record ran on real TPU silicon even when the plugin
        # platform is not named 'tpu' (e.g. axon)
        record['device_kind'] = device_kind
    if os.environ.get('SE3_TPU_CODE_REV'):
        # sessions pin the package-tree fingerprint at chip acquisition;
        # carrying it in the record ties every number to the code that
        # produced it (the 01:39Z picker-regression record was only
        # identifiable by timestamp — BENCH_SESSION.jsonl, round 4)
        record['code_rev'] = os.environ['SE3_TPU_CODE_REV']
    if fallback_reason:
        record['fallback_reason'] = fallback_reason
    if fast_fallback:
        record['fast_fallback'] = True
    if is_tpu:
        # FLOP accounting (corrected round 4): XLA cost_analysis is
        # doubly blind on this program — Pallas-kernel FLOPs are
        # invisible AND lax.map (edge_chunks) bodies count once instead
        # of trip-count times. The r03 records' "MFU 0.0027" was that
        # artifact (utils/flops.py docstring has the audit numbers); the
        # analytic count is the honest one and both are recorded.
        t_step = dt / steps
        if step_flops:
            record['step_tflops_xla_visible'] = round(step_flops / 1e12, 3)
        try:
            # the whole block inside the guard: an import/estimator
            # failure after the timed run must not lose the record
            from se3_transformer_tpu.utils.flops import (
                PEAK_BF16, PEAK_F32, train_step_flops_estimate,
            )
            # module.num_neighbors is authoritative (the recipe built the
            # model; bench's local is just the label)
            fl = train_step_flops_estimate(module, num_nodes,
                                           module.num_neighbors, batch)
            record['step_tflops_analytic'] = round(fl / 1e12, 2)
            record['mfu_f32_analytic'] = round(fl / t_step / PEAK_F32, 4)
            record['mfu_bf16_analytic'] = round(fl / t_step / PEAK_BF16, 4)
            if fl / t_step > PEAK_BF16:
                # sustaining more than bf16 peak is physically impossible
                # for this program: the executable cannot have run the
                # labelled computation (19:29Z artifact class)
                record['implausible_throughput'] = True
        except Exception as e:  # noqa: BLE001 - estimator scope (no EGNN)
            print(f'flop estimate failed ({type(e).__name__}: {e})',
                  file=sys.stderr)
    print(json.dumps(record))
    return record


def ring_main(n_devices: int, per_device_nodes: int = None):
    """`python bench.py --ring N`: sequence-parallel comm A/B on an
    N-virtual-device CPU mesh (sp=N ring-path training step, fixed
    per-device nodes — the scripts/width_table.py --weak-scaling harness,
    shared so the numbers are the same program PERF.md tables).

    Prints ONE bench-shaped JSON line whose value is the
    overlapped+sparse arm's nodes·steps/s; the serialized+dense control
    arm rides along (`overlapped_vs_serialized`) with BOTH arms' schema'd
    `comm` payloads — collective classes/bytes and the full-width
    all-gather scan of each traced HLO (parallel.exchange.comm_payload),
    the same end-to-end A/B discipline as --pipelined (never compared
    against the single-device RECORD anchors: different program).

    CPU-mesh caveat travels with the record: all virtual devices share
    this host's cores, so overlap cannot hide transfer latency here —
    the honest CPU-side win is the all-gather-free trace + flat
    per-shard memory; overlap is measured for regression, not for the
    ICI story (that needs a real pod)."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'scripts'))
    import width_table

    if per_device_nodes is None:
        per_device_nodes = int(os.environ.get('SE3_TPU_RING_PDN', 64))
    jax = width_table._setup(n_devices)
    arms = {}
    for overlap, exchange, arm in ((True, True, 'overlapped_sparse'),
                                   (False, False, 'serialized_dense')):
        arms[arm] = width_table.weak_scaling_point(
            jax, n_devices, per_device_nodes, dim=16, k=8,
            overlap=overlap, exchange=exchange)
    fast_arm = arms['overlapped_sparse']
    n = fast_arm['n']
    record = {
        'metric': f'ring_comm_ab_nodes_steps_per_sec'
                  f'(sp={n_devices},pdn={per_device_nodes},dim=16)',
        'value': round(n / fast_arm['step_s'], 2),
        'unit': 'nodes*steps/sec/cpu-host',
        'vs_baseline': 1.0,  # own-program A/B; RECORD anchors don't apply
        'mode': 'ring_ab',
        'sp': n_devices,
        'n': n,
        'step_s': fast_arm['step_s'],
        'serialized_dense_step_s': arms['serialized_dense']['step_s'],
        'overlapped_vs_serialized': round(
            arms['serialized_dense']['step_s'] / fast_arm['step_s'], 3),
        'per_shard_total_gb': fast_arm.get('per_shard_total_gb'),
        'comm': {arm: rec.get('comm') for arm, rec in arms.items()},
        'cost': {arm: rec.get('cost') for arm, rec in arms.items()},
        'loss_finite': bool(fast_arm.get('loss_finite')
                            and arms['serialized_dense'].get('loss_finite')),
    }
    if os.environ.get('SE3_TPU_CODE_REV'):
        record['code_rev'] = os.environ['SE3_TPU_CODE_REV']
    print(json.dumps(record))
    return record


def mesh_main(dp: int, sp: int, tp: int, per_device_nodes: int = None):
    """`python bench.py --mesh dp,sp,tp`: composed-parallelism A/B on
    the virtual CPU mesh (ROADMAP item 4). Arm A runs the dp x sp x tp
    train step through the explicit-aliasing composed route
    (scripts/width_table.py mesh_sweep_point — the same program the
    MESH_SWEEP.jsonl bank rows come from); arm B runs the IDENTICAL
    global problem (same batch, same node count) as plain (dp, 1, 1)
    data parallelism. Placement is the only difference, so the ratio
    isolates what composing sp and tp costs/buys on this host.

    Prints ONE bench-shaped JSON line whose value is the composed arm's
    nodes*steps/s; the dp-only control rides along
    (`composed_vs_dp_only`) with BOTH arms' schema'd `comm` payloads —
    per-class AND per-mesh-axis collective bytes plus the axis-aware
    full-width all-gather scan — and both cost-ledger payloads. Same
    CPU-mesh caveat as --ring: virtual devices share this host's cores,
    so wall-clock ratios measure regression, not the ICI story; the
    transferable wins are the all-gather-free proof bit and the
    per-shard memory column. Never compared against the single-device
    RECORD anchors: different program."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'scripts'))
    import width_table

    if per_device_nodes is None:
        per_device_nodes = int(os.environ.get('SE3_TPU_MESH_PDN', 64))
    n_devices = dp * sp * tp
    jax = width_table._setup(max(n_devices, 2))
    arms = {
        'composed': width_table.mesh_sweep_point(
            jax, dp, sp, tp, per_device_nodes, dim=16, k=8),
        # same global shapes: b=dp, n=per_device_nodes*sp, on (dp,1,1)
        'dp_only': width_table.mesh_sweep_point(
            jax, dp, 1, 1, per_device_nodes * sp, dim=16, k=8),
    }
    composed = arms['composed']
    n = composed['n']
    assert arms['dp_only']['n'] == n, 'arms must share global shapes'
    record = {
        'metric': f'mesh_comm_ab_nodes_steps_per_sec'
                  f'(dp={dp},sp={sp},tp={tp},pdn={per_device_nodes},'
                  f'dim=16)',
        'value': round(n / composed['step_s'], 2),
        'unit': 'nodes*steps/sec/cpu-host',
        'vs_baseline': 1.0,  # own-program A/B; RECORD anchors don't apply
        'mode': 'mesh_ab',
        'dp': dp, 'sp': sp, 'tp': tp,
        'n': n,
        'step_s': composed['step_s'],
        'dp_only_step_s': arms['dp_only']['step_s'],
        'composed_vs_dp_only': round(
            arms['dp_only']['step_s'] / composed['step_s'], 3),
        'per_shard_total_gb': composed.get('per_shard_total_gb'),
        'dp_only_per_shard_total_gb':
            arms['dp_only'].get('per_shard_total_gb'),
        'comm': {arm: rec.get('comm') for arm, rec in arms.items()},
        'cost': {arm: rec.get('cost') for arm, rec in arms.items()},
        'loss_finite': bool(composed.get('loss_finite')
                            and arms['dp_only'].get('loss_finite')),
    }
    if os.environ.get('SE3_TPU_CODE_REV'):
        record['code_rev'] = os.environ['SE3_TPU_CODE_REV']
    print(json.dumps(record))
    return record


def flash_main(steps: int = 6, n: int = 128, k: int = 16,
               num_degrees: int = 4, dim: int = 16):
    """`python bench.py --flash`: fused-vs-XLA streaming-attention A/B
    on the CPU toy bench (the ISSUE 11 acceptance harness).

    Builds the SAME conv-weighted attention toy model twice — the
    unfused trunk (materialized basis + gathered/keyed features +
    scores) and the fuse_pairwise streaming path
    (kernels.pallas_flash, identical parameters) — and measures a
    jitted value_and_grad TRAIN step per arm, best-of-two windows.
    Peak HBM comes from the PR 6 cost ledger on each arm's compiled
    executable, so the before/after activation-memory claim is a
    ledger entry, not prose. Prints ONE bench-shaped JSON line whose
    value is the fused arm's nodes*steps/s; scripts/flash_smoke.py
    wraps the payload into the schema'd `flash` record and
    PERF_BUDGETS.json enforces the step-time and peak-HBM wins plus
    the fused equivariance gate. Never compared against the RECORD
    anchors: different program."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.models.se3_transformer import (
        SE3TransformerModule,
    )
    from se3_transformer_tpu.observability.costs import cost_payload
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    from se3_transformer_tpu.utils.validation import equivariance_l2

    enable_compilation_cache()
    rng = np.random.RandomState(0)
    feats = jnp.asarray(rng.normal(size=(1, n, dim)), jnp.float32)
    coors = jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                        jnp.float32)
    mask = jnp.ones((1, n), bool)
    kw = dict(dim=dim, depth=1, num_degrees=num_degrees,
              output_degrees=2, reduce_dim_out=True, attend_self=True,
              use_null_kv=True, num_neighbors=k, heads=2, dim_head=8,
              tie_key_values=True, shared_radial_hidden=True)
    unfused = SE3TransformerModule(**kw)
    fused = SE3TransformerModule(fuse_pairwise=True, **kw)
    params = jax.jit(fused.init, static_argnames=('return_type',))(
        jax.random.PRNGKey(0), feats, coors, mask=mask,
        return_type=1)['params']

    arms = {}
    for arm, mod in (('unfused', unfused), ('fused', fused)):
        def loss(p, mod=mod):
            out = mod.apply({'params': p}, feats, coors, mask=mask,
                            return_type=1)
            return (out ** 2).mean()
        compiled = jax.jit(jax.value_and_grad(loss)).lower(
            params).compile()
        cost = cost_payload(compiled, label=f'flash_ab_{arm}')
        _, g = compiled(params)
        jax.block_until_ready(g)                  # warmup
        arms[arm] = dict(compiled=compiled, cost=cost,
                         peak_hbm_bytes=cost['peak_bytes'], best=None)
    # ALTERNATING windows (the tune_kernels A/B-pair discipline): a
    # monotonic host-load drift then hits both arms equally instead of
    # whichever arm happened to run second
    for _ in range(3):
        for arm in ('unfused', 'fused'):
            compiled = arms[arm]['compiled']
            t0 = time.monotonic()
            for _ in range(steps):
                _, g = compiled(params)
            jax.block_until_ready(g)
            dt = (time.monotonic() - t0) / steps
            if arms[arm]['best'] is None or dt < arms[arm]['best']:
                arms[arm]['best'] = dt
    for arm in ('unfused', 'fused'):
        arms[arm]['step_ms'] = round(arms[arm].pop('best') * 1e3, 2)
        del arms[arm]['compiled']
        print(f'{arm}: {arms[arm]["step_ms"]} ms/step, peak '
              f'{arms[arm]["peak_hbm_bytes"] / 2**20:.1f} MiB',
              file=sys.stderr)

    out_u = unfused.apply({'params': params}, feats, coors, mask=mask,
                          return_type=1)
    out_f = fused.apply({'params': params}, feats, coors, mask=mask,
                        return_type=1)
    parity = float(jnp.abs(out_u - out_f).max())
    eq = equivariance_l2(fused, params, feats, coors, mask)

    # global (graph-free) scenario: the large-assembly variant with NO
    # kNN truncation — streaming per-tile rel_pos/radial/payload vs the
    # materialized all-pairs formulation of the same function. Guarded:
    # a failure here must not lose the kNN A/B already measured.
    global_payload = None
    try:
        global_payload = _flash_global_ab(steps=max(2, steps // 2))
    except Exception as e:  # noqa: BLE001
        print(f'global-scenario A/B failed ({type(e).__name__}: {e}); '
              f'recording the kNN A/B without it', file=sys.stderr)

    fused_s = arms['fused']['step_ms'] / 1e3
    record = {
        'metric': f'flash_attention_ab_nodes_steps_per_sec'
                  f'(dim={dim},n={n},k={k},deg={num_degrees},'
                  f'backend=cpu)',
        'value': round(n / fused_s, 2),
        'unit': 'nodes*steps/sec/cpu-host',
        'vs_baseline': 1.0,     # own-program A/B; anchors don't apply
        'mode': 'flash_ab',
        'timing': 'best-of-3-alternating',
        'fused_step_ms': arms['fused']['step_ms'],
        'unfused_step_ms': arms['unfused']['step_ms'],
        'fused_vs_unfused': round(
            arms['unfused']['step_ms'] / arms['fused']['step_ms'], 3),
        'parity_l2': parity,
        'equivariance_l2_fused': eq,
        'peak_hbm_fused': arms['fused']['peak_hbm_bytes'],
        'peak_hbm_unfused': arms['unfused']['peak_hbm_bytes'],
        'hbm_unfused_vs_fused': round(
            arms['unfused']['peak_hbm_bytes']
            / max(arms['fused']['peak_hbm_bytes'], 1), 3),
        'cost': {arm: rec['cost'] for arm, rec in arms.items()},
    }
    if global_payload is not None:
        record['global'] = global_payload
    if os.environ.get('SE3_TPU_CODE_REV'):
        record['code_rev'] = os.environ['SE3_TPU_CODE_REV']
    print(json.dumps(record))
    return record


def _flash_global_ab(n: int = 192, steps: int = 3):
    """Streaming global attention vs the materialized all-pairs
    reference (forward, one output degree): step ms + ledgered peak
    bytes both arms. The payload the --flash record carries for the
    graph-free scenario."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.kernels import pallas_flash as pf
    from se3_transformer_tpu.observability.costs import cost_payload

    rng = np.random.RandomState(3)
    B, heads, kv_h, dim_head, mid = 1, 2, 2, 8, 32
    pairs = ((0, 8), (1, 8))
    d_out = 1
    Dh = dim_head * (2 * d_out + 1)
    IF = sum(c * (2 * min(d, d_out) + 1) for d, c in pairs)
    O = kv_h * dim_head
    q = jnp.asarray(rng.normal(size=(B, n, heads, Dh)), jnp.float32)
    xs = tuple(jnp.asarray(rng.normal(size=(B, n, c, 2 * d + 1)),
                           jnp.float32) for d, c in pairs)
    coords = jnp.asarray(rng.normal(size=(B, n, 3)) * 2, jnp.float32)
    rp = tuple(jnp.asarray(rng.normal(size=s), jnp.float32) * 0.3
               for s in [(1, mid), (mid,), (mid,), (mid,), (mid, mid),
                         (mid,), (mid,), (mid,)])
    wv = jnp.asarray(rng.normal(size=(mid, IF, O)), jnp.float32)
    bv = jnp.asarray(rng.normal(size=(IF, O)), jnp.float32)
    scale = dim_head ** -0.5
    cfg = pf.FlashConfig(pairs=pairs, d_out=d_out, heads=heads,
                         kv_heads=kv_h, scale=scale, arm_v='dense',
                         arm_k='dense', tie=True)
    consts = {k: jnp.asarray(v, jnp.float32)
              for k, v in pf._arm_consts(cfg).items()}

    def streaming(c):
        return pf.flash_global_attention(
            q, xs, c, rp, wv, bv, pairs=pairs, d_out=d_out, heads=heads,
            kv_heads=kv_h, scale=scale, arm='dense', pallas=False)

    def materialized(c):
        rel = c[:, :, None, :] - c[:, None, :, :]
        h = pf._radial_apply(pf._safe_dist(rel)[..., None], rp)
        sh = pf.flash_sh_payload(rel, pf._sh_degree(cfg),
                                 differentiable=True)
        xg = tuple(jnp.broadcast_to(x[:, None], (B, n, *x.shape[1:]))
                   for x in xs)
        kv = pf._kv_block('dense', pairs, d_out, xg, h, sh, None, wv,
                          bv, consts).reshape(B, n, n, kv_h, Dh)
        notself = (jnp.arange(n)[:, None] != jnp.arange(n)[None])[None]
        return pf._row_attention(cfg, q, kv, kv, notself)

    out = {}
    parity = None
    fns = dict(streaming=streaming, materialized=materialized)
    compiled = {}
    results = {}
    for arm, fn in fns.items():
        compiled[arm] = jax.jit(fn).lower(coords).compile()
        cost = cost_payload(compiled[arm], label=f'flash_global_{arm}')
        results[arm] = compiled[arm](coords)
        jax.block_until_ready(results[arm])
        out[arm] = dict(peak_hbm_bytes=cost['peak_bytes'], best=None)
    parity = float(jnp.abs(results['streaming']
                           - results['materialized']).max())
    for _ in range(2):      # alternating windows, like the kNN A/B
        for arm in fns:
            t0 = time.monotonic()
            for _ in range(steps):
                r = compiled[arm](coords)
            jax.block_until_ready(r)
            dt = (time.monotonic() - t0) / steps
            if out[arm]['best'] is None or dt < out[arm]['best']:
                out[arm]['best'] = dt
    for arm in fns:
        out[arm]['step_ms'] = round(out[arm].pop('best') * 1e3, 2)
    return dict(
        n=n, parity_l2=parity,
        streaming_step_ms=out['streaming']['step_ms'],
        materialized_step_ms=out['materialized']['step_ms'],
        peak_hbm_streaming=out['streaming']['peak_hbm_bytes'],
        peak_hbm_materialized=out['materialized']['peak_hbm_bytes'],
        hbm_materialized_vs_streaming=round(
            out['materialized']['peak_hbm_bytes']
            / max(out['streaming']['peak_hbm_bytes'], 1), 3))


def assembly_main(ns=(256, 512), steps: int = 3, dim: int = 8):
    """`python bench.py --assembly n1,n2,...`: kNN-free global-vs-
    materialized large-assembly A/B on the CPU toy MODEL (the ISSUE 18
    acceptance harness; the kernel-level pair lives in --flash's
    `global` payload).

    Builds the SAME attention_mode='global' model twice — the streaming
    arm (O(n) activation memory, per-tile pair payload) and the
    global_materialize=True control arm (every [b, n, n, ...] per-edge
    tensor in HBM, plain autodiff) — with IDENTICAL parameters, and
    measures a jitted forward per arm per n in alternating best-of-2
    windows. Peak HBM per arm comes from the PR 6 cost ledger on each
    compiled executable, so the memory claim is a ledger entry, not
    prose (the --ring / --degrees discipline). Prints ONE bench-shaped
    JSON line whose value is the largest-n streaming arm's
    nodes*steps/s; scripts/assembly_smoke.py wraps the serving-side
    variant into the schema'd `assembly` record and PERF_BUDGETS.json
    enforces the >=3x HBM floor + equivariance. Never compared against
    the RECORD anchors: different program."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.models.se3_transformer import (
        SE3TransformerModule,
    )
    from se3_transformer_tpu.observability.costs import cost_payload
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    kw = dict(num_tokens=24, dim=dim, depth=1, num_degrees=2,
              output_degrees=2, reduce_dim_out=True, attend_self=True,
              use_null_kv=True, heads=2, dim_head=8, pallas=False,
              attention_mode='global')
    mods = {'global': SE3TransformerModule(**kw),
            'materialized': SE3TransformerModule(
                **kw, global_materialize=True)}

    rng = np.random.RandomState(0)
    params = None
    points = {}
    for n in ns:
        feats = jnp.asarray(rng.randint(0, 24, (1, n)))
        coors = jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                            jnp.float32)
        mask = jnp.ones((1, n), bool)
        if params is None:
            # one seeded tree serves every n and BOTH arms (the params
            # are n-independent; identical-params parity is the point)
            params = jax.jit(
                mods['global'].init,
                static_argnames=('return_type',))(
                jax.random.PRNGKey(0), feats, coors, mask=mask,
                return_type=1)['params']

        arms = {}
        results = {}
        for arm, mod in mods.items():
            def fn(f, c, m, _mod=mod):
                return _mod.apply({'params': params}, f, c, mask=m,
                                  return_type=1)
            compiled = jax.jit(fn).lower(feats, coors, mask).compile()
            cost = cost_payload(compiled,
                                label=f'assembly_{arm},n={n},dim={dim}')
            results[arm] = compiled(feats, coors, mask)
            jax.block_until_ready(results[arm])
            arms[arm] = dict(compiled=compiled, cost=cost,
                             peak_hbm_bytes=cost['peak_bytes'], best=None)
        parity = float(jnp.abs(results['global']
                               - results['materialized']).max())
        for _ in range(2):      # alternating windows (the --flash idiom)
            for arm, rec in arms.items():
                t0 = time.monotonic()
                for _ in range(steps):
                    r = rec['compiled'](feats, coors, mask)
                jax.block_until_ready(r)
                dt = (time.monotonic() - t0) / steps
                if rec['best'] is None or dt < rec['best']:
                    rec['best'] = dt
        entry = dict(
            n=n, parity_linf=parity,
            global_step_ms=round(arms['global']['best'] * 1e3, 2),
            materialized_step_ms=round(
                arms['materialized']['best'] * 1e3, 2),
            peak_hbm_global=arms['global']['peak_hbm_bytes'],
            peak_hbm_materialized=arms['materialized']['peak_hbm_bytes'],
            hbm_materialized_vs_global=round(
                arms['materialized']['peak_hbm_bytes']
                / max(arms['global']['peak_hbm_bytes'], 1), 3),
            cost={arm: rec['cost'] for arm, rec in arms.items()})
        points[str(n)] = entry
        print(f'n={n}: {entry["global_step_ms"]} ms/step streaming vs '
              f'{entry["materialized_step_ms"]} ms materialized, HBM '
              f'ratio {entry["hbm_materialized_vs_global"]}, parity '
              f'{parity:.2e}', file=sys.stderr)

    top = str(max(ns))
    record = {
        'metric': f'assembly_ab_nodes_steps_per_sec'
                  f'(dim={dim},ns={",".join(str(n) for n in ns)},'
                  f'backend=cpu)',
        'value': round(max(ns) / (points[top]['global_step_ms'] / 1e3), 2),
        'unit': 'nodes*steps/sec/cpu-host',
        'vs_baseline': 1.0,     # own-program A/B; anchors don't apply
        'mode': 'assembly_ab',
        'timing': 'best-of-2-alternating',
        'points': points,
    }
    if os.environ.get('SE3_TPU_CODE_REV'):
        record['code_rev'] = os.environ['SE3_TPU_CODE_REV']
    print(json.dumps(record))
    return record


def quant_main(mix: str = 'int8_mix', steps: int = 5,
               buckets=(12, 24), batch_size: int = 2,
               eq_degrees=(2, 4)):
    """`python bench.py --quant [int8_mix|bf16|fp8_mix]`: fp32-vs-
    quantized-mix serving A/B on the CPU toy engines (the ROADMAP
    item 3 acceptance harness).

    Builds THREE AOT engines from ONE seeded param tree — fp32, the
    quantized mix (restore-time quantization: the fp32 tree never
    lands on device), and the fp32 REFERENCE of the same quantized
    weights (dequantized host-side) — and measures engine.run latency
    per bucket in alternating best-of-3 windows. Three claims land as
    record fields, not prose:

      * argument_bytes_ratio — quantized/fp32 argument bytes off each
        bucket's PR 6 cost ledger (the per-replica memory claim;
        budget ceiling 0.6);
      * parity_max_abs — quantized engine vs the fp32 reference OF THE
        SAME QUANTIZED WEIGHTS, padded AND unpadded rows (the serving
        implementation must add nothing beyond quantization itself;
        gated at the repo-wide 1e-4 bar). The error vs the RAW fp32
        engine is quant_error_max_abs — the accuracy tradeoff a mix
        buys its memory with, banked per record (an absolute 1e-4
        there is mathematically unreachable for any int8 weight grid:
        per-channel rounding alone is ~0.4% relative);
      * equivariance_l2 — worst-case over feats models at
        `eq_degrees`, quantized params (weight-only quantization must
        preserve equivariance to roundoff).

    Prints ONE bench-shaped JSON line; scripts/quant_smoke.py wraps
    the payload into the schema'd `quant_ab` record and
    PERF_BUDGETS.json enforces ratio + parity + equivariance. Never
    compared against the RECORD anchors: different program."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu import quant
    from se3_transformer_tpu.inference import InferenceEngine
    from se3_transformer_tpu.models.se3_transformer import (
        SE3TransformerModule,
    )
    from se3_transformer_tpu.native.loader import chain_adjacency
    from se3_transformer_tpu.training.denoise import DenoiseConfig
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    from se3_transformer_tpu.utils.validation import equivariance_l2

    enable_compilation_cache()
    buckets = tuple(int(b) for b in buckets)
    rng = np.random.RandomState(0)
    cfg = DenoiseConfig(num_tokens=24, dim=8, dim_head=8, heads=2,
                        depth=2, num_degrees=2, max_sparse_neighbors=4)
    module = cfg.build_module()
    L = buckets[0]
    params = jax.jit(module.init, static_argnames=('return_type',))(
        jax.random.PRNGKey(0),
        jnp.asarray(rng.randint(0, cfg.num_tokens, size=(1, L))),
        jnp.asarray(rng.normal(size=(1, L, 3)).astype(np.float32)),
        mask=jnp.ones((1, L), bool),
        adj_mat=jnp.asarray(chain_adjacency(L)),
        return_type=1)['params']
    host_params = jax.tree_util.tree_map(np.asarray, params)

    qtree, quant_report = quant.quantize_params(host_params, mix)
    # the fp32 reference OF THE QUANTIZED WEIGHTS: dequantize (and
    # upcast the bf16 casts) host-side — the implementation-parity
    # oracle every fused epilogue must match
    ref_tree = jax.tree_util.tree_map(
        lambda x: quant.dequantize(x)
        if isinstance(x, quant.QuantTensor)
        else (np.asarray(x, np.float32)
              if getattr(x, 'dtype', None) == jnp.bfloat16 else x),
        qtree, is_leaf=lambda x: isinstance(x, quant.QuantTensor))

    engines = {
        'fp32': InferenceEngine(module, host_params, buckets=buckets,
                                batch_size=batch_size),
        'quant': InferenceEngine(module, host_params, buckets=buckets,
                                 batch_size=batch_size, precision=mix),
        'ref': InferenceEngine(module, ref_tree, buckets=buckets,
                               batch_size=batch_size),
    }

    # one padded + one unpadded request set per bucket (fixed across
    # arms so the comparison is input-identical)
    requests = {}
    for b in buckets:
        full = (rng.randint(0, cfg.num_tokens, size=b),
                rng.normal(size=(b, 3)).astype(np.float32))
        short_len = max(1, b - 3)
        short = (rng.randint(0, cfg.num_tokens, size=short_len),
                 rng.normal(size=(short_len, 3)).astype(np.float32))
        requests[b] = (full, short)

    outputs = {arm: {} for arm in engines}
    for arm, engine in engines.items():
        for b, (full, short) in requests.items():
            outputs[arm][b] = (np.asarray(engine.predict(*full)),
                               np.asarray(engine.predict(*short)))
    parity = max(float(np.abs(outputs['quant'][b][i]
                              - outputs['ref'][b][i]).max())
                 for b in buckets for i in (0, 1))
    quant_error = max(float(np.abs(outputs['quant'][b][i]
                                   - outputs['fp32'][b][i]).max())
                      for b in buckets for i in (0, 1))

    # ALTERNATING windows per bucket (the tune_kernels A/B-pair
    # discipline): host-load drift hits both arms equally
    per_bucket = {b: {'fp32': None, 'quant': None} for b in buckets}
    from se3_transformer_tpu.native.loader import pad_to_bucket
    for _ in range(3):
        for arm in ('fp32', 'quant'):
            engine = engines[arm]
            for b in buckets:
                tok, crd = requests[b][0]
                t, c, m = pad_to_bucket([tok], [crd], b,
                                        batch_size=batch_size)
                t0 = time.monotonic()
                for _ in range(steps):
                    out = engine.run(b, t, c, m)
                jax.block_until_ready(out)
                dt = (time.monotonic() - t0) / steps
                best = per_bucket[b][arm]
                if best is None or dt < best:
                    per_bucket[b][arm] = dt

    bucket_entries = {}
    for b in buckets:
        f_ms = per_bucket[b]['fp32'] * 1e3
        q_ms = per_bucket[b]['quant'] * 1e3
        bucket_entries[str(b)] = dict(
            fp32_ms=round(f_ms, 3), quant_ms=round(q_ms, 3),
            quant_vs_fp32=round(f_ms / q_ms, 3))

    # the memory claim off the cost ledger: argument bytes of the
    # LARGEST bucket's executable, per arm (params dominate; the
    # request arrays are identical between arms)
    top = buckets[-1]
    costs = {arm: engines[arm].cost_payloads[engines[arm]._key(top)]
             for arm in ('fp32', 'quant')}
    arg_fp32 = costs['fp32']['memory']['argument_bytes']
    arg_quant = costs['quant']['memory']['argument_bytes']

    # equivariance at the swept degrees: feats models, quantized params
    eq_by_degree = {}
    n, k, dim = 64, 8, 8
    feats = jnp.asarray(rng.normal(size=(1, n, dim)), jnp.float32)
    coors = jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                        jnp.float32)
    mask = jnp.ones((1, n), bool)
    for d in eq_degrees:
        mod = SE3TransformerModule(
            dim=dim, depth=1, num_degrees=d + 1, output_degrees=2,
            reduce_dim_out=True, attend_self=True, num_neighbors=k,
            heads=2, dim_head=8, num_conv_layers=2, tie_key_values=True)
        dparams = jax.jit(mod.init, static_argnames=('return_type',))(
            jax.random.PRNGKey(0), feats, coors, mask=mask,
            return_type=1)['params']
        dq, _ = quant.quantize_params(
            jax.tree_util.tree_map(np.asarray, dparams), mix)
        eq_by_degree[str(d)] = equivariance_l2(mod, dq, feats, coors,
                                               mask)

    record = {
        'metric': f'quant_ab_{mix}(dim={cfg.dim},depth={cfg.depth},'
                  f'buckets={",".join(str(b) for b in buckets)},'
                  f'backend=cpu)',
        'value': bucket_entries[str(top)]['quant_vs_fp32'],
        'unit': 'quant_vs_fp32_step_ratio',
        'vs_baseline': 1.0,     # own-program A/B; anchors don't apply
        'mode': 'quant_ab',
        'timing': 'best-of-3-alternating',
        'mix': quant_report['mix'],
        'buckets': bucket_entries,
        'argument_bytes_fp32': arg_fp32,
        'argument_bytes_quant': arg_quant,
        'argument_bytes_ratio': round(arg_quant / max(arg_fp32, 1), 4),
        'params_bytes_ratio': quant_report['bytes_ratio'],
        'quant_report': quant_report,
        'parity_max_abs': parity,
        'quant_error_max_abs': quant_error,
        'equivariance_l2': max(eq_by_degree.values()),
        'equivariance_by_degree': eq_by_degree,
        'cost': {arm: dict(body) for arm, body in costs.items()},
    }
    if os.environ.get('SE3_TPU_CODE_REV'):
        record['code_rev'] = os.environ['SE3_TPU_CODE_REV']
    for arm in ('fp32', 'quant'):
        print(f"{arm}: {bucket_entries[str(top)][f'{arm}_ms']} ms/step "
              f"@ bucket {top}, argument bytes "
              f"{costs[arm]['memory']['argument_bytes']}",
              file=sys.stderr)
    print(f'impl parity {parity:.2e}, quant error {quant_error:.2e}, '
          f'worst eq {record["equivariance_l2"]:.2e}', file=sys.stderr)
    print(json.dumps(record))
    return record


def degrees_main(degrees, dense_max: int = 4, steps: int = 5):
    """`python bench.py --degrees 2,4,6`: per-degree so2-vs-dense A/B on
    the CPU toy bench (the ROADMAP item 2 acceptance harness).

    For each max degree d, builds the SAME conv-weighted toy model (two
    preconv layers + one attention block, tied k/v — the conv
    contraction is the term the backends differ on) twice — dense CG
    backend and the so2 banded backend, IDENTICAL parameters — and
    times the jitted forward, best-of-two windows of `steps` fixed-batch
    applies each. The dense arm runs only at degrees <= `dense_max`
    (default 4): the dense basis at degree 6 needs the full degree-6
    Q_J intertwiners, whose one-time host Sylvester solves take tens of
    minutes on a cold cache — exactly the cost class the so2 backend
    exists to avoid (its canonical blocks ship as a committed seed).

    Prints ONE bench-shaped JSON line whose value is the so2 arm's
    nodes*steps/s at the highest swept degree; the per-degree payload
    (`degrees`: dense/so2 step ms, dense_vs_so2 ratio, so2 equivariance
    L2, dense-vs-so2 parity where dense ran) is what scripts/
    so2_smoke.py wraps into the schema'd `so2_sweep` record and what
    the committed perf budgets judge (PERF_BUDGETS.json:
    so2_degree4_beats_dense / so2_degree4_throughput_floor). Never
    compared against the RECORD anchors: different program."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.models.se3_transformer import (
        SE3TransformerModule,
    )
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    from se3_transformer_tpu.utils.validation import equivariance_l2

    enable_compilation_cache()
    n, k, dim = 128, 12, 8
    rng = np.random.RandomState(0)
    feats = jnp.asarray(rng.normal(size=(1, n, dim)), jnp.float32)
    coors = jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                        jnp.float32)
    mask = jnp.ones((1, n), bool)

    from se3_transformer_tpu.observability.costs import cost_payload

    def bench_forward(mod, params, label):
        fwd = jax.jit(lambda c: mod.apply({'params': params}, feats, c,
                                          mask=mask, return_type=1))
        # AOT-compile so the SAME executable serves the cost ledger and
        # the timed windows (the --ring / --flash discipline): each
        # arm's peak-HBM split is a ledger entry, not prose
        compiled = fwd.lower(coors).compile()
        cost = cost_payload(compiled, label=label)
        out = compiled(coors)
        out.block_until_ready()                       # warmup
        best = None
        for _ in range(2):
            t0 = time.monotonic()
            for _ in range(steps):
                out = compiled(coors)
            out.block_until_ready()
            dt = (time.monotonic() - t0) / steps
            best = dt if best is None or dt < best else best
        return best, cost

    per_degree = {}
    for d in degrees:
        kw = dict(dim=dim, depth=1, num_degrees=d + 1, output_degrees=2,
                  reduce_dim_out=True, attend_self=True, num_neighbors=k,
                  heads=2, dim_head=8, num_conv_layers=2,
                  tie_key_values=True)
        so2_mod = SE3TransformerModule(conv_backend='so2', **kw)
        # init through the so2 module: identical param tree, and at
        # degrees > dense_max it never touches the dense basis' Q_J
        params = jax.jit(so2_mod.init,
                         static_argnames=('return_type',))(
            jax.random.PRNGKey(0), feats, coors, mask=mask,
            return_type=1)['params']
        so2_s, so2_cost = bench_forward(so2_mod, params,
                                        f'so2_sweep_d{d}_so2')
        entry = dict(
            so2_step_ms=round(so2_s * 1e3, 2),
            so2_nodes_steps_per_sec=round(n / so2_s, 2),
            equivariance_l2_so2=equivariance_l2(so2_mod, params, feats,
                                                coors, mask),
            so2_peak_hbm_bytes=so2_cost['peak_bytes'],
            cost={'so2': so2_cost})
        if d <= dense_max:
            dense_mod = SE3TransformerModule(**kw)
            out_d = dense_mod.apply({'params': params}, feats, coors,
                                    mask=mask, return_type=1)
            out_s = so2_mod.apply({'params': params}, feats, coors,
                                  mask=mask, return_type=1)
            entry['parity_l2'] = float(jnp.abs(out_d - out_s).max())
            dense_s, dense_cost = bench_forward(dense_mod, params,
                                                f'so2_sweep_d{d}_dense')
            entry['dense_step_ms'] = round(dense_s * 1e3, 2)
            entry['dense_vs_so2'] = round(dense_s / so2_s, 3)
            # per-arm peak-HBM split: the so2 memory claim rides the
            # ledger (like --ring's per-arm cost payloads), not prose
            entry['dense_peak_hbm_bytes'] = dense_cost['peak_bytes']
            entry['cost']['dense'] = dense_cost
        per_degree[str(d)] = entry
        print(f'degree {d}: {entry}', file=sys.stderr)

    top = str(max(degrees))
    record = {
        'metric': f'so2_degree_sweep(dim={dim},n={n},k={k},ncl=2,'
                  f'degrees={",".join(str(d) for d in degrees)},'
                  f'backend=cpu)',
        'value': per_degree[top]['so2_nodes_steps_per_sec'],
        'unit': 'nodes*steps/sec/cpu-host',
        'vs_baseline': 1.0,     # own-program A/B; anchors don't apply
        'mode': 'so2_sweep',
        'timing': 'best-of-2',
        'degrees': per_degree,
    }
    if os.environ.get('SE3_TPU_CODE_REV'):
        record['code_rev'] = os.environ['SE3_TPU_CODE_REV']
    print(json.dumps(record))
    return record


def v2_degrees_main(degrees, so2_max: int = 6, steps: int = 5):
    """`python bench.py --v2-degrees 2,4,6,8`: per-degree A/B of the v2
    eSCN-direct model family against the v1+so2 baseline on the CPU toy
    bench (the SE3TransformerV2 acceptance harness).

    Unlike --degrees this is a MODEL-FAMILY A/B, not a backend A/B on
    identical parameters — v2 is deliberately not checkpoint-compatible
    with v1 (its radial trunks emit per-m banded blocks directly, no
    dense-shaped radial output exists to share), so each arm inits its
    own params and the comparison is per-step wall clock + peak HBM off
    the cost ledger + the v2 arm's equivariance L2. The v1+so2 arm runs
    only at degrees <= `so2_max` (default 6): its per-degree canonical-
    block compile grows steeply on CPU, and past the crossover the v2
    arm is the only one worth timing — exactly the regime the family
    exists for.

    Prints ONE bench-shaped JSON line whose value is the v2 arm's
    nodes*steps/s at the highest swept degree; the per-degree payload
    (`degrees`: v2 step ms / throughput / equivariance / peak HBM,
    so2 step ms and so2_vs_v2 where the baseline ran) is what
    scripts/v2_smoke.py wraps into the schema'd `v2_sweep` record and
    what the committed budgets judge (PERF_BUDGETS.json:
    v2_degree6_beats_so2 / v2_degree6_throughput_floor /
    v2_equivariance_gate_degree_max). Never compared against the
    RECORD anchors: different program."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import jax.numpy as jnp
    import numpy as np

    from se3_transformer_tpu.models.se3_transformer import (
        SE3TransformerModule,
    )
    from se3_transformer_tpu.observability.costs import cost_payload
    from se3_transformer_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )
    from se3_transformer_tpu.utils.validation import equivariance_l2
    from se3_transformer_tpu.v2 import SE3TransformerV2Module

    enable_compilation_cache()
    n, k, dim = 128, 12, 8
    rng = np.random.RandomState(0)
    feats = jnp.asarray(rng.normal(size=(1, n, dim)), jnp.float32)
    coors = jnp.asarray(np.cumsum(rng.normal(size=(1, n, 3)), axis=1),
                        jnp.float32)
    mask = jnp.ones((1, n), bool)

    def bench_arm(mod, label):
        params = jax.jit(mod.init, static_argnames=('return_type',))(
            jax.random.PRNGKey(0), feats, coors, mask=mask,
            return_type=1)['params']
        fwd = jax.jit(lambda c: mod.apply({'params': params}, feats, c,
                                          mask=mask, return_type=1))
        # AOT-compile so the SAME executable serves the cost ledger and
        # the timed windows (the --degrees discipline): each arm's
        # peak-HBM claim is a ledger entry, not prose
        compiled = fwd.lower(coors).compile()
        cost = cost_payload(compiled, label=label)
        out = compiled(coors)
        out.block_until_ready()                       # warmup
        best = None
        for _ in range(2):
            t0 = time.monotonic()
            for _ in range(steps):
                out = compiled(coors)
            out.block_until_ready()
            dt = (time.monotonic() - t0) / steps
            best = dt if best is None or dt < best else best
        return best, cost, params

    per_degree = {}
    for d in degrees:
        v2_mod = SE3TransformerV2Module(
            dim=dim, depth=2, num_degrees=d + 1, output_degrees=2,
            reduce_dim_out=True, num_neighbors=k)
        v2_s, v2_cost, v2_params = bench_arm(v2_mod, f'v2_sweep_d{d}_v2')
        entry = dict(
            v2_step_ms=round(v2_s * 1e3, 2),
            v2_nodes_steps_per_sec=round(n / v2_s, 2),
            equivariance_l2_v2=equivariance_l2(v2_mod, v2_params, feats,
                                               coors, mask),
            v2_peak_hbm_bytes=v2_cost['peak_bytes'],
            cost={'v2': v2_cost})
        if d <= so2_max:
            so2_mod = SE3TransformerModule(
                dim=dim, depth=1, num_degrees=d + 1, output_degrees=2,
                reduce_dim_out=True, attend_self=True, num_neighbors=k,
                heads=2, dim_head=8, num_conv_layers=2,
                tie_key_values=True, conv_backend='so2',
                shared_radial_hidden=True)
            so2_s, so2_cost, _ = bench_arm(so2_mod, f'v2_sweep_d{d}_so2')
            entry['so2_step_ms'] = round(so2_s * 1e3, 2)
            entry['so2_vs_v2'] = round(so2_s / v2_s, 3)
            entry['so2_peak_hbm_bytes'] = so2_cost['peak_bytes']
            entry['cost']['so2'] = so2_cost
        per_degree[str(d)] = entry
        print(f'degree {d}: {entry}', file=sys.stderr)

    top = str(max(degrees))
    record = {
        'metric': f'v2_degree_sweep(dim={dim},n={n},k={k},'
                  f'degrees={",".join(str(d) for d in degrees)},'
                  f'backend=cpu)',
        'value': per_degree[top]['v2_nodes_steps_per_sec'],
        'unit': 'nodes*steps/sec/cpu-host',
        'vs_baseline': 1.0,     # own-program A/B; anchors don't apply
        'mode': 'v2_sweep',
        'timing': 'best-of-2',
        'degrees': per_degree,
    }
    if os.environ.get('SE3_TPU_CODE_REV'):
        record['code_rev'] = os.environ['SE3_TPU_CODE_REV']
    print(json.dumps(record))
    return record


if __name__ == '__main__':
    if '--flash' in sys.argv[1:]:
        # CPU A/B harness (no device probe, like --degrees): streaming
        # fused attention vs the unfused trunk, flags parsed before jax
        # initializes its backends
        _steps = 6
        if '--steps' in sys.argv[1:]:
            _steps = int(sys.argv[sys.argv.index('--steps') + 1])
        flash_main(steps=_steps)
        sys.exit(0)
    if '--assembly' in sys.argv[1:]:
        # CPU A/B harness (no device probe, like --degrees): streaming
        # global attention vs the materialized all-pairs control arm
        # at each requested n, flags parsed before jax initializes
        _i = sys.argv.index('--assembly')
        _ns = [int(x) for x in sys.argv[_i + 1].split(',')] \
            if len(sys.argv) > _i + 1 \
            and not sys.argv[_i + 1].startswith('--') else [256, 512]
        _steps = 3
        if '--steps' in sys.argv[1:]:
            _steps = int(sys.argv[sys.argv.index('--steps') + 1])
        assembly_main(tuple(_ns), steps=_steps)
        sys.exit(0)
    if '--quant' in sys.argv[1:]:
        # CPU A/B harness (no device probe, like --degrees): fp32 vs a
        # quantized precision mix over the serving engines, flags
        # parsed before jax initializes its backends
        _i = sys.argv.index('--quant')
        _mix = sys.argv[_i + 1] if len(sys.argv) > _i + 1 and \
            not sys.argv[_i + 1].startswith('--') else 'int8_mix'
        _steps = 5
        if '--steps' in sys.argv[1:]:
            _steps = int(sys.argv[sys.argv.index('--steps') + 1])
        quant_main(mix=_mix, steps=_steps)
        sys.exit(0)
    if '--v2-degrees' in sys.argv[1:]:
        # CPU A/B harness (no device probe, like --degrees): per-degree
        # v2-vs-(v1+so2) model-family comparison, flags parsed before
        # jax initializes its backends
        _i = sys.argv.index('--v2-degrees')
        _degs = [int(x) for x in sys.argv[_i + 1].split(',')] \
            if len(sys.argv) > _i + 1 else [2, 4]
        _sm = 6
        if '--so2-max' in sys.argv[1:]:
            _sm = int(sys.argv[sys.argv.index('--so2-max') + 1])
        _steps = 5
        if '--steps' in sys.argv[1:]:
            _steps = int(sys.argv[sys.argv.index('--steps') + 1])
        v2_degrees_main(_degs, so2_max=_sm, steps=_steps)
        sys.exit(0)
    if '--degrees' in sys.argv[1:]:
        # CPU A/B harness (no device probe, like --ring): per-degree
        # so2-vs-dense comparison, flags parsed before jax initializes
        _i = sys.argv.index('--degrees')
        _degs = [int(x) for x in sys.argv[_i + 1].split(',')] \
            if len(sys.argv) > _i + 1 else [2, 4]
        _dm = 4
        if '--dense-max' in sys.argv[1:]:
            _dm = int(sys.argv[sys.argv.index('--dense-max') + 1])
        degrees_main(_degs, dense_max=_dm)
        sys.exit(0)
    if '--ring' in sys.argv[1:]:
        # CPU-mesh harness: no device probe (the TPU tunnel is a single
        # chip — the sp story needs virtual devices), flags parsed before
        # jax initializes its backends
        _i = sys.argv.index('--ring')
        _n = int(sys.argv[_i + 1]) if len(sys.argv) > _i + 1 else 8
        ring_main(_n)
        sys.exit(0)
    if '--mesh' in sys.argv[1:]:
        # composed dp x sp x tp A/B on the virtual CPU mesh, same
        # no-device-probe discipline as --ring
        _i = sys.argv.index('--mesh')
        _spec = sys.argv[_i + 1] if len(sys.argv) > _i + 1 else '2,2,2'
        _dp, _sp, _tp = (int(x) for x in _spec.split(','))
        mesh_main(_dp, _sp, _tp)
        sys.exit(0)
    _pipelined = '--pipelined' in sys.argv[1:]
    _backend, _reason = _device_backend_or_cpu()
    main(_backend, fallback_reason=_reason, pipelined=_pipelined)
